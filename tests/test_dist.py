"""Multi-process distributed kvstore tests — no real cluster.

reference idiom (SURVEY.md §4): tests/nightly/dist_sync_kvstore.py run via
`tools/launch.py -n 3 --launcher local`; workers assert allreduced values.
Here each worker is a CPU-platform process joined by jax.distributed.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse as sp

kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
out = {}

# dense push/pull with server-side optimizer
kv.init(0, nd.array(np.zeros((4,), np.float32)))
kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0,
                                     rescale_grad=1.0))
kv.push(0, nd.array(np.full((4,), float(rank + 1), np.float32)))
dst = nd.array(np.zeros((4,), np.float32))
kv.pull(0, out=dst)
# sum over ranks of (rank+1) = nw*(nw+1)/2, sgd lr 1 → w = -sum
out["dense"] = dst.asnumpy().tolist()

# rowsparse: each worker touches its own row
kv.init(1, nd.array(np.zeros((8, 2), np.float32)))
g = sp.row_sparse_array((np.ones((1, 2), np.float32), [rank]), shape=(8, 2))
kv.push(1, g)
rs = sp.zeros("row_sparse", (8, 2))
kv.row_sparse_pull(1, out=rs, row_ids=nd.array(np.arange(8)))
out["rsp"] = rs.tostype("default").asnumpy().tolist()

# gradient compression path
kv2_key = 2
kv.init(kv2_key, nd.array(np.zeros((3,), np.float32)))
kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
kv.push(kv2_key, nd.array(np.array([1.0, -1.0, 0.1], np.float32)))
c = nd.array(np.zeros((3,), np.float32))
kv.pull(kv2_key, out=c)
out["compressed"] = c.asnumpy().tolist()

out["rank"] = rank
out["nw"] = nw
with open(os.environ["RESULT_FILE_PREFIX"] + str(rank) + ".json", "w") as f:
    json.dump(out, f)
"""


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_dist_sync_kvstore_local_launcher(tmp_path):
    n = 2
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = dict(os.environ)
    env.update({
        "RESULT_FILE_PREFIX": str(tmp_path / "result_"),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--root-port", str(_free_port()),
         sys.executable, str(script)],
        env=env, timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-3000:]
    results = []
    for r in range(n):
        with open(str(tmp_path / ("result_%d.json" % r))) as f:
            results.append(json.load(f))
    total = n * (n + 1) / 2
    for res in results:
        assert res["nw"] == n
        # dense: sgd applied once to the allreduced grad
        np.testing.assert_allclose(res["dense"], [-total] * 4)
        # rowsparse: every worker's row got -1 (its own push, allreduced)
        rsp = np.asarray(res["rsp"])
        for r in range(n):
            np.testing.assert_allclose(rsp[r], [-1.0, -1.0])
        assert np.abs(rsp[n:]).sum() == 0
        # compression: |0.1| < threshold quantized to 0, ±1 → ±0.5 per worker
        np.testing.assert_allclose(res["compressed"],
                                   [-0.5 * n, 0.5 * n, 0.0])


def test_launch_tpu_emits_spec():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--launcher", "tpu", "echo", "train"],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    assert "DMLC_WORKER_ID=0" in proc.stdout
    assert "DMLC_WORKER_ID=1" in proc.stdout


BUCKET_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import engine, nd, telemetry

kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
rng = np.random.RandomState(100 + rank)
shapes = [(64, 3, 3), (64,), (128, 64), (128,), (10, 128), (10,)]
keys = list(range(len(shapes)))
grads = [nd.array(rng.randn(*s).astype(np.float32)) for s in shapes]

# bucketed multi-key pushpull (default 25 MB cap)
for k, s in zip(keys, shapes):
    kv.init(k, nd.zeros(s))
before = dict(telemetry.snapshot()["counters"])
outs = [nd.zeros(s) for s in shapes]
kv.pushpull(keys, grads, out=outs)
after = dict(telemetry.snapshot()["counters"])
bucketed = [o.asnumpy() for o in outs]
n_coll = (after.get("comm.collectives", 0)
          - before.get("comm.collectives", 0))

# per-key escape hatch on fresh keys, same grads
with engine.bucket_mb_scope(0):
    for j, s in enumerate(shapes):
        kv.init(100 + j, nd.zeros(s))
    outs2 = [nd.zeros(s) for s in shapes]
    kv.pushpull([100 + j for j in range(len(shapes))], grads, out=outs2)
flat = [o.asnumpy() for o in outs2]

out = {
    "rank": rank, "nw": nw, "collectives": n_coll,
    "bitexact": all(np.array_equal(a, b) for a, b in zip(bucketed, flat)),
    "sum0": bucketed[0].sum().item(),
}
with open(os.environ["RESULT_FILE_PREFIX"] + str(rank) + ".json", "w") as f:
    json.dump(out, f)
"""


@pytest.mark.slow
def test_dist_bucketed_pushpull_parity_two_workers(tmp_path):
    """ISSUE 4 satellite: dist-kvstore bucketed vs per-key gradients are
    bit-identical across a real 2-process allreduce, and the bucketed sync
    launches one collective for the whole 6-key set."""
    n = 2
    script = tmp_path / "bucket_worker.py"
    script.write_text(BUCKET_WORKER)
    env = dict(os.environ)
    env.update({
        "RESULT_FILE_PREFIX": str(tmp_path / "result_"),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_TPU_COMM_BUCKET_MB", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--root-port", str(_free_port()),
         sys.executable, str(script)],
        env=env, timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-3000:]
    sums = set()
    for r in range(n):
        with open(str(tmp_path / ("result_%d.json" % r))) as f:
            res = json.load(f)
        assert res["nw"] == n
        assert res["bitexact"], "bucketed != per-key on rank %d" % r
        assert res["collectives"] == 1, res["collectives"]
        sums.add(round(res["sum0"], 4))
    # allreduced result is identical on every rank
    assert len(sums) == 1


COMMIT_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import resilience as rz
from mxnet_tpu.resilience import commit

kv = mx.kv.create("dist_sync")  # rendezvous: brings up the coordinator
rank, nw = kv.rank, kv.num_workers

# each rank checkpoints to its OWN directory (per-host local disk shape)
ck = rz.SnapshotCheckpointer(
    os.path.join(os.environ["CKPT_ROOT"], "rank_%d" % rank), keep=None)
for step in (1, 2, 3, 4):
    ck.save(step, {"w": np.full((2,), float(step)), "step": step})
# rank 1 "crashed mid-commit a step ahead": step-5 payload durable, marker
# never flipped
if rank == 1:
    ck.prepare(5, {"w": np.full((2,), 5.0), "step": 5})

# restore election over the real jax.distributed coordinator: every rank
# reports its newest DURABLE step; the fleet restores the elected min
durable = max(ck.prepared_steps())
coord = commit.CommitCoordinator()
elected = coord.elect(durable, kind="restore")
step, tree = ck.restore(elected)

# a second election round (the save path) proves round ids do not collide
elected2 = coord.elect(step, kind="save")

out = {"rank": rank, "nw": nw, "durable": durable, "elected": elected,
       "restored_step": step, "restored_payload": int(tree["step"]),
       "elected2": elected2}
with open(os.environ["RESULT_FILE_PREFIX"] + str(rank) + ".json", "w") as f:
    json.dump(out, f)
"""


@pytest.mark.slow
def test_dist_commit_election_rank_ahead_by_one(tmp_path):
    """ISSUE 5 satellite: a rank that crashed mid-commit one step ahead —
    step-5 payload durable on rank 1 only, marker still at 4 — restores
    the ELECTED min step (4) on every rank, over the real jax.distributed
    coordinator."""
    n = 2
    script = tmp_path / "commit_worker.py"
    script.write_text(COMMIT_WORKER)
    env = dict(os.environ)
    env.update({
        "RESULT_FILE_PREFIX": str(tmp_path / "result_"),
        "CKPT_ROOT": str(tmp_path / "ckpts"),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_TPU_FAULT_PLAN", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--root-port", str(_free_port()),
         sys.executable, str(script)],
        env=env, timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-3000:]
    results = []
    for r in range(n):
        with open(str(tmp_path / ("result_%d.json" % r))) as f:
            results.append(json.load(f))
    by_rank = {res["rank"]: res for res in results}
    assert by_rank[0]["durable"] == 4
    assert by_rank[1]["durable"] == 5, "rank 1 must be a step ahead"
    for res in results:
        assert res["nw"] == n
        assert res["elected"] == 4, \
            "every rank must elect the fleet min: %r" % (res,)
        assert res["restored_step"] == 4
        assert res["restored_payload"] == 4
        assert res["elected2"] == 4


TRACE_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry

kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers

# bucketed multi-key pushpull -> comm.bucket[...] spans on each rank
shapes = [(64, 32), (64,), (32, 16)]
keys = list(range(len(shapes)))
rng = np.random.RandomState(rank)
for k, s in zip(keys, shapes):
    kv.init(k, nd.zeros(s))
grads = [nd.array(rng.randn(*s).astype(np.float32)) for s in shapes]
outs = [nd.zeros(s) for s in shapes]
kv.pushpull(keys, grads, out=outs)
outs[0].asnumpy()
with telemetry.span("rank_marker_%d" % rank, "test"):
    pass

# collective: BOTH ranks call the merged dump in lockstep; each writes its
# own copy of the SAME fleet-wide trace
path = telemetry.dump_trace(
    os.environ["TRACE_FILE_PREFIX"] + str(rank) + ".json", merged=True)

out = {"rank": rank, "nw": nw, "trace_id": telemetry.trace_id(),
       "path": path}
with open(os.environ["RESULT_FILE_PREFIX"] + str(rank) + ".json", "w") as f:
    json.dump(out, f)
"""


@pytest.mark.slow
def test_dist_merged_trace_two_workers(tmp_path):
    """ISSUE 6 acceptance: `dump_trace(merged=True)` from a 2-rank run
    yields ONE chrome trace with both ranks' comm-bucket spans as separate
    process rows on a shared clock, under one run-wide trace id."""
    n = 2
    script = tmp_path / "trace_worker.py"
    script.write_text(TRACE_WORKER)
    env = dict(os.environ)
    env.update({
        "RESULT_FILE_PREFIX": str(tmp_path / "result_"),
        "TRACE_FILE_PREFIX": str(tmp_path / "trace_"),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_TPU_TELEMETRY", None)
    env.pop("MXNET_TPU_TRACE_ID", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--root-port", str(_free_port()),
         sys.executable, str(script)],
        env=env, timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-3000:]
    results = []
    for r in range(n):
        with open(str(tmp_path / ("result_%d.json" % r))) as f:
            results.append(json.load(f))
    # one run-wide trace id, adopted by every rank during the exchange
    assert results[0]["trace_id"] == results[1]["trace_id"]
    for res in results:
        obj = json.load(open(res["path"]))
        meta = obj["metadata"]
        assert meta["merged"] is True
        assert meta["ranks"] == [0, 1]
        assert meta["trace_id"] == results[0]["trace_id"]
        spans = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        by_rank = {0: set(), 1: set()}
        for e in spans:
            by_rank[e["pid"]].add(e["name"])
        # both ranks contributed their comm-bucket spans AND their marker
        for r in (0, 1):
            assert any(name.startswith("comm.bucket[")
                       for name in by_rank[r]), \
                "rank %d has no comm-bucket span in the merged trace" % r
            assert ("rank_marker_%d" % r) in by_rank[r]


FLEET_WORKER = r"""
import json, os, sys, urllib.request
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.parallel.dist import coordinator_client
from mxnet_tpu.telemetry import export, federation

kv = mx.kv.create("dist_sync")   # rendezvous only — federation is the
rank, nw = kv.rank, kv.num_workers   # out-of-band path, no collectives
port = int(os.environ["FLEET_PORT%d" % rank])
server = export.start_http_server(port, host="127.0.0.1")
telemetry.inc("fleet.probe", rank + 1)       # rank-distinct values

# coordination-service barrier (no XLA collective): both endpoints up +
# counters set before rank 0 scrapes
client = coordinator_client()
client.wait_at_barrier("fleet_up", 60000)

out = {"rank": rank, "nw": nw}
if rank == 0:
    federation.configure(["127.0.0.1:%s" % os.environ["FLEET_PORT1"]])
    fleet = json.loads(urllib.request.urlopen(
        "http://127.0.0.1:%d/fleet/snapshot" % port, timeout=15).read())
    text = urllib.request.urlopen(
        "http://127.0.0.1:%d/fleet/metrics" % port,
        timeout=15).read().decode()
    out["workers"] = fleet["workers"]
    out["stale"] = fleet["stale_ranks"] + fleet["missing"]
    out["ranks"] = sorted(fleet["ranks"])
    out["merged_probe"] = fleet["merged"]["counters"].get("fleet.probe")
    out["probe_r0"] = fleet["ranks"]["0"]["snapshot"]["counters"].get(
        "fleet.probe")
    out["probe_r1"] = fleet["ranks"]["1"]["snapshot"]["counters"].get(
        "fleet.probe")
    out["rank0_series"] = 'mxnet_tpu_fleet_probe{rank="0"} 1' in text
    out["rank1_series"] = 'mxnet_tpu_fleet_probe{rank="1"} 2' in text

# second barrier: rank 1's endpoint must outlive rank 0's scrape
client.wait_at_barrier("fleet_done", 60000)
with open(os.environ["RESULT_FILE_PREFIX"] + str(rank) + ".json", "w") as f:
    json.dump(out, f)
"""


@pytest.mark.slow
def test_dist_fleet_scrape_federation_two_workers(tmp_path):
    """ISSUE 12 acceptance: /fleet/metrics on rank 0 of a real 2-process
    run serves BOTH ranks' rank-labeled series in one scrape, and
    /fleet/snapshot merges both ranks' counters with no stale ranks."""
    n = 2
    script = tmp_path / "fleet_worker.py"
    script.write_text(FLEET_WORKER)
    env = dict(os.environ)
    env.update({
        "RESULT_FILE_PREFIX": str(tmp_path / "result_"),
        "FLEET_PORT0": str(_free_port()),
        "FLEET_PORT1": str(_free_port()),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_TPU_TELEMETRY", None)
    env.pop("MXNET_TPU_FLEET_PEERS", None)
    env.pop("MXNET_TPU_METRICS_PORT", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--root-port", str(_free_port()),
         sys.executable, str(script)],
        env=env, timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-3000:]
    with open(str(tmp_path / "result_0.json")) as f:
        res = json.load(f)
    assert res["nw"] == n
    assert res["workers"] == 2
    assert res["stale"] == []
    assert res["ranks"] == ["0", "1"]
    # counters merged fleet-wide (1 + 2) AND preserved per rank
    assert res["merged_probe"] == 3
    assert res["probe_r0"] == 1 and res["probe_r1"] == 2
    # ONE scrape carries both ranks' rank-labeled Prometheus series
    assert res["rank0_series"] and res["rank1_series"]


ZERO_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry

kv = mx.kv.create("dist_sync")
rank, nw = kv.rank, kv.num_workers
# integer-valued grads: the cross-worker sum is exact regardless of the
# reduce order, so ZeRO (psum_scatter) and replicated (psum) agree bitwise
shapes = [(8, 4), (16,), (4, 4), (32,)]   # 112 elems, divisible by 2
rng = np.random.RandomState(7)
init_w = [rng.randint(-4, 5, s).astype(np.float32) for s in shapes]
grads = [[np.random.RandomState(100 * step + rank)
          .randint(-3, 4, s).astype(np.float32) for s in shapes]
         for step in range(3)]

def run(zero, base_key):
    kv.set_optimizer(mx.optimizer.create(
        "sgd", learning_rate=0.125, momentum=0.5, rescale_grad=1.0),
        zero=zero)
    keys = [base_key + i for i in range(len(shapes))]
    for k, w in zip(keys, init_w):
        kv.init(k, nd.array(w))
    for step in range(3):
        kv.push(keys, [nd.array(g) for g in grads[step]])
    outs = [nd.zeros(s) for s in shapes]
    kv.pull(keys, out=outs)
    return [o.asnumpy() for o in outs]

zero_out = run(True, 0)
gauges = dict(telemetry.snapshot()["gauges"])
repl_out = run(False, 100)

total_state = sum(int(np.prod(s)) for s in shapes) * 4  # momentum fp32
out = {
    "rank": rank, "nw": nw,
    "bitexact": all(np.array_equal(a, b)
                    for a, b in zip(zero_out, repl_out)),
    "sum0": float(zero_out[0].sum()),
    "state_bytes": gauges.get("opt.state_bytes_per_rank", {}).get("value"),
    "replicated_state_bytes": total_state,
}
with open(os.environ["RESULT_FILE_PREFIX"] + str(rank) + ".json", "w") as f:
    json.dump(out, f)
"""


@pytest.mark.slow
def test_dist_zero_parity_two_workers(tmp_path):
    """ISSUE 9 satellite: ZeRO weight-update sharding across a real
    2-process fleet — final params bit-identical to the replicated
    dist update on every rank, and each rank's measured optimizer-state
    footprint is exactly half the replicated total."""
    n = 2
    script = tmp_path / "zero_worker.py"
    script.write_text(ZERO_WORKER)
    env = dict(os.environ)
    env.update({
        "RESULT_FILE_PREFIX": str(tmp_path / "result_"),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_TPU_COMM_BUCKET_MB", None)
    env.pop("MXNET_TPU_ZERO", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--root-port", str(_free_port()),
         sys.executable, str(script)],
        env=env, timeout=300, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr[-3000:]
    sums = set()
    for r in range(n):
        with open(str(tmp_path / ("result_%d.json" % r))) as f:
            res = json.load(f)
        assert res["nw"] == n
        assert res["bitexact"], "zero != replicated on rank %d" % r
        # Adam-memory-/-world acceptance shape: momentum bytes halve
        assert res["state_bytes"] == res["replicated_state_bytes"] // n, res
        sums.add(round(res["sum0"], 4))
    assert len(sums) == 1   # all-gathered weights identical on every rank


# ---------------------------------------------------------------------------
# 2-bit compression wire format (unit; reference: gradient_compression.cc)
# ---------------------------------------------------------------------------

def test_two_bit_packing_bytes_on_wire():
    import jax.numpy as jnp
    from mxnet_tpu.kvstore.kvstore_dist import GradientCompression
    gc = GradientCompression(threshold=0.5)
    g = np.array([1.0, -2.0, 0.1, 0.6, -0.5, 0.0, 0.0], np.float32)
    packed = gc.compress("k", jnp.asarray(g))
    # 7 values -> 2 bytes on the wire (4 values/byte), not 28 float bytes
    assert packed.dtype == np.uint8
    assert packed.nbytes == 2
    back = np.asarray(gc.decompress(packed, g.shape, g.dtype))
    np.testing.assert_array_equal(back, [0.5, -0.5, 0, 0.5, -0.5, 0, 0])


def test_two_bit_error_feedback_accumulates():
    import jax.numpy as jnp
    from mxnet_tpu.kvstore.kvstore_dist import GradientCompression
    gc = GradientCompression(threshold=0.5)
    g = jnp.asarray(np.array([0.3, -0.3], np.float32))
    # 0.3 < t: first push sends 0, residual carries 0.3; second push's
    # accumulated 0.6 crosses the threshold
    p1 = gc.compress("k", g)
    b1 = np.asarray(gc.decompress(p1, (2,), np.float32))
    np.testing.assert_array_equal(b1, [0, 0])
    p2 = gc.compress("k", g)
    b2 = np.asarray(gc.decompress(p2, (2,), np.float32))
    np.testing.assert_array_equal(b2, [0.5, -0.5])


def test_two_bit_packing_2d_and_padding():
    import jax.numpy as jnp
    from mxnet_tpu.kvstore.kvstore_dist import GradientCompression
    gc = GradientCompression(threshold=1.0)
    rng = np.random.RandomState(0)
    g = rng.randn(5, 7).astype(np.float32) * 2
    packed = gc.compress("k", jnp.asarray(g))
    assert packed.nbytes == (35 + 3) // 4
    back = np.asarray(gc.decompress(packed, g.shape, g.dtype))
    expect = np.where(g >= 1.0, 1.0, np.where(g <= -1.0, -1.0, 0.0))
    np.testing.assert_array_equal(back, expect.astype(np.float32))
