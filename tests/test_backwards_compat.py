"""Saved-model backward compatibility against frozen fixture files.

reference: tests/nightly/model_backwards_compatibility_check/ — models
saved by an earlier version must load and produce identical outputs.
The fixtures under tests/fixtures/ were written by the round-4 build and
are committed verbatim; these tests are the contract that future format
changes stay readable. DO NOT regenerate the fixtures to make a failing
test pass — that inverts the guarantee.
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.gluon import nn

FIX = os.path.join(os.path.dirname(__file__), "fixtures")


def _mlp():
    net = nn.HybridSequential(prefix="mlp_")
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    return net


def test_gluon_params_fixture_loads_exact():
    net = _mlp()
    net.load_parameters(os.path.join(FIX, "mlp_r4.params"))
    x = nd.array(onp.load(os.path.join(FIX, "mlp_r4_input.npy")))
    want = onp.load(os.path.join(FIX, "mlp_r4_output.npy"))
    onp.testing.assert_allclose(net(x).asnumpy(), want, rtol=1e-6,
                                atol=1e-6)


def test_symbol_json_fixture_loads():
    sym = mx.sym.load(os.path.join(FIX, "mlp_r4-symbol.json"))
    assert sym.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"]
    shapes, _, _ = sym.infer_shape(data=(2, 5))
    assert shapes[1] == (8, 5) and shapes[3] == (3, 8)


def test_trainer_states_fixture_loads():
    net = _mlp()
    net.load_parameters(os.path.join(FIX, "mlp_r4_after_step.params"))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    tr.load_states(os.path.join(FIX, "mlp_r4.states"))
    # momentum buffers restored: a zero-gradient step must still move
    # parameters (momentum carry), not leave them unchanged
    before = {k: v.data().asnumpy().copy()
              for k, v in net.collect_params().items()}
    from mxnet_tpu import autograd
    x = nd.array(onp.load(os.path.join(FIX, "mlp_r4_input.npy")))
    with autograd.record():
        loss = (net(x) * 0.0).sum()
    loss.backward()
    tr.step(1)
    moved = any(
        not onp.allclose(v.data().asnumpy(), before[k])
        for k, v in net.collect_params().items())
    assert moved, "restored momentum state had no effect"


def test_ndarray_dict_fixture_exact_values():
    loaded = nd.load(os.path.join(FIX, "ndarray_dict_r4.params"))
    assert set(loaded) == {"w_f32", "w_f16", "w_i32", "w_bf16"}
    onp.testing.assert_array_equal(
        loaded["w_f32"].asnumpy(),
        onp.arange(6, dtype="float32").reshape(2, 3))
    assert str(loaded["w_f16"].dtype) == "float16"
    assert str(loaded["w_i32"].dtype) == "int32"
    assert str(loaded["w_bf16"].dtype) == "bfloat16"
    onp.testing.assert_array_equal(
        loaded["w_bf16"].astype("float32").asnumpy(), [1.5, -2.5])
    onp.testing.assert_array_equal(loaded["w_i32"].asnumpy(), [1, -2, 3])
