"""Autograd tape (reference suite: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x * x)
    y.backward()
    expected = 2 * 2.0 * np.exp(4.0)
    assert np.allclose(x.grad.asnumpy(), [expected], rtol=1e-5)


def test_multi_input():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a
    c.backward()
    assert np.allclose(a.grad.asnumpy(), [4, 5])
    assert np.allclose(b.grad.asnumpy(), [1, 2])


def test_head_gradient():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3 * x
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30, 300])


def test_grad_req_add():
    x = nd.array([1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])
    x.zero_grad()
    assert np.allclose(x.grad.asnumpy(), [0.0])


def test_grad_req_write_overwrites():
    x = nd.array([1.0])
    x.attach_grad()  # write
    for _ in range(3):
        with autograd.record():
            y = 2 * x
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [2.0])


def test_detach_blocks_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [4.0])  # only d(y_const * x)/dx = y


def test_blockgrad_op():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = nd.BlockGrad(x * x) + x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [1.0])


def test_is_recording_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    assert not autograd.is_recording()


def test_no_record_no_grad():
    x = nd.array([1.0])
    x.attach_grad()
    y = x * 5  # outside record
    with autograd.record():
        z = x * 3
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [3.0])


def test_autograd_grad_function():
    x = nd.array([3.0])
    with autograd.record():
        y = x * x
    g = autograd.grad(y, x)
    assert np.allclose(g.asnumpy(), [6.0])
    assert x.grad is None or np.allclose(x.grad.asnumpy(), [0.0])


def test_matrix_grad():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 2).astype(np.float32))
    a.attach_grad()
    with autograd.record():
        c = nd.dot(a, b)
        loss = c.sum()
    loss.backward()
    assert np.allclose(a.grad.asnumpy(), b.asnumpy().sum(axis=1)[None, :].repeat(3, 0),
                       atol=1e-5)


def test_broadcast_grad():
    x = nd.ones((2, 3))
    bias = nd.zeros((3,))
    bias.attach_grad()
    with autograd.record():
        y = (x + bias).sum()
    y.backward()
    assert np.allclose(bias.grad.asnumpy(), [2, 2, 2])


def test_reused_variable():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [12.0])


def test_multiple_heads():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = x * 3
    autograd.backward([y, z])
    assert np.allclose(x.grad.asnumpy(), [5.0, 5.0])


def test_custom_function():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return 2 * x * dy

    x = nd.array([3.0])
    x.attach_grad()
    f = Square()
    with autograd.record():
        y = f(x)
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [6.0])


def test_softmax_output_fused_grad():
    data = nd.array(np.random.randn(4, 5).astype(np.float32))
    label = nd.array([0, 1, 2, 3], dtype="float32")
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward()
    sm = out.asnumpy()
    oh = np.eye(5)[[0, 1, 2, 3]]
    assert np.allclose(data.grad.asnumpy(), sm - oh, atol=1e-5)


def test_dropout_respects_mode():
    x = nd.ones((100, 100))
    with autograd.record(train_mode=False):
        y = nd.Dropout(x, p=0.5)
    assert np.all(y.asnumpy() == 1.0)
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    frac = (y.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7


def test_partial_multioutput_backward():
    """Only one output of a multi-output op feeds the loss."""
    x = nd.array(np.arange(8, dtype=np.float32))
    x.attach_grad()
    with autograd.record():
        a, b = nd.split(x, num_outputs=2, axis=0)
        loss = (a * 2).sum()
    loss.backward()
    assert np.allclose(x.grad.asnumpy(), [2, 2, 2, 2, 0, 0, 0, 0])


def test_split_v2_grad():
    x = nd.array(np.arange(6, dtype=np.float32))
    x.attach_grad()
    with autograd.record():
        parts = nd.split_v2(x, 3)
        loss = parts[1].sum() * 5
    loss.backward()
    assert np.allclose(x.grad.asnumpy(), [0, 0, 5, 5, 0, 0])


def test_inplace_under_record_raises():
    """reference semantics: in-place ops on tape-involved arrays while
    recording raise."""
    import mxnet_tpu as mx
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with pytest.raises(mx.MXNetError):
            y += 1
        with pytest.raises(mx.MXNetError):
            x += 1  # x was consumed by the mul
    # outside recording both are fine
    y += 1
    x += 1


def test_grad_does_not_clobber_backward_grad():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    saved = x.grad.asnumpy().copy()
    with autograd.record():
        z = x * 5
    g = autograd.grad(z, x)
    assert np.allclose(g.asnumpy(), [5.0])
    assert np.allclose(x.grad.asnumpy(), saved)  # untouched


def test_dropout_mode_always():
    x = nd.ones((64, 64))
    y = nd.Dropout(x, p=0.5, mode="always")  # outside any train scope
    frac = (y.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7


# ---------------------------------------------------------------------------
# higher-order autograd: create_graph=True (reference: python/mxnet/autograd.py
# (grad) — grad-of-grad)
# ---------------------------------------------------------------------------

def test_grad_create_graph_second_order():
    """d2/dx2 of x^3 = 6x, via grad(create_graph=True) then backward()."""
    x = nd.array(np.array([1.5, -2.0, 0.5], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        dx = autograd.grad(y, x, create_graph=True)
        z = dx.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 6.0 * x.asnumpy(),
                               rtol=1e-5)


def test_grad_create_graph_vs_finite_difference():
    """Hessian-vector via double grad matches finite differences of the
    first gradient, through a multi-op chain."""
    rng = np.random.RandomState(0)
    xv = rng.randn(4).astype(np.float32)
    x = nd.array(xv)
    x.attach_grad()

    def f(t):
        return (t * t.exp() + nd.sin(t * 0.5)).sum()

    with autograd.record():
        y = f(x)
        dx = autograd.grad(y, x, create_graph=True)
        s = (dx * dx).sum()          # uses the differentiable first grad
    s.backward()
    # finite difference of g(x) = sum(grad_f(x)^2)
    eps = 1e-3
    def g(v):
        t = nd.array(v.astype(np.float32))
        t.attach_grad()
        with autograd.record():
            yy = f(t)
        yy.backward()
        return float((t.grad * t.grad).sum().asnumpy())
    fd = np.array([(g(xv + eps * e) - g(xv - eps * e)) / (2 * eps)
                   for e in np.eye(4, dtype=np.float32)])
    np.testing.assert_allclose(x.grad.asnumpy(), fd, rtol=2e-2, atol=2e-2)


def test_grad_create_graph_third_order():
    """x^4: third derivative 24x via grad -> grad -> backward."""
    x = nd.array(np.array([1.0, 2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x * x) * (x * x)
        d1 = autograd.grad(y, x, create_graph=True)      # 4x^3
        d2 = autograd.grad(d1.sum(), x, create_graph=True)  # 12x^2
        s = d2.sum()
    s.backward()                                          # 24x
    np.testing.assert_allclose(x.grad.asnumpy(), 24.0 * x.asnumpy(),
                               rtol=1e-4)


def test_grad_create_graph_custom_function_raises():
    class MyFn(autograd.Function):
        def forward(self, a):
            return a * 2
        def backward(self, dy):
            return dy * 2

    x = nd.array(np.ones(3, np.float32))
    x.attach_grad()
    fn = MyFn()
    with autograd.record():
        y = fn(x)
        try:
            autograd.grad(y.sum(), x, create_graph=True)
            raised = False
        except NotImplementedError as e:
            raised = True
            assert "MyFn" in str(e)
    assert raised


def test_grad_create_graph_multi_head_and_head_grads():
    x = nd.array(np.array([2.0, 3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y1 = x * x        # dy1/dx = 2x
        y2 = x * x * x    # dy2/dx = 3x^2
        dx = autograd.grad([y1, y2], x, create_graph=True,
                           head_grads=[nd.ones((2,)), None])
        s = dx.sum()      # d/dx (2x + 3x^2) = 2 + 6x
    s.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2.0 + 6.0 * x.asnumpy(),
                               rtol=1e-5)


def test_grad_create_graph_stops_at_variables():
    """Nodes strictly upstream of `variables` are constants of the
    differentiation: a primal-less custom Function there must not raise."""
    class MyFn(autograd.Function):
        def forward(self, a):
            return a * 2
        def backward(self, dy):
            return dy * 2

    x = nd.array(np.array([1.0, 2.0], np.float32))
    fn = MyFn()
    with autograd.record():
        y = fn(x)          # primal-less node, upstream of the variable
        y.attach_grad()    # mark y itself (grad wrt y, not x)
        z = y * y
        dy = autograd.grad(z, y, create_graph=True)  # must not raise
        s = dy.sum()
    s.backward()
    # d2z/dy2 = 2
    np.testing.assert_allclose(y.grad.asnumpy(), [2.0, 2.0], rtol=1e-6)


def test_getitem_on_tape_basic_and_advanced():
    """Slicing under record() must flow gradients (round-5 find: raw views
    silently detached the tape; reference: slice/gather ops have
    FGradient)."""
    x = nd.array(np.ones((3, 4), np.float32))
    x.attach_grad()
    with autograd.record():
        loss = (x * 2.0)[:, :2].sum() + x[1].sum()
    loss.backward()
    g = x.grad.asnumpy()
    np.testing.assert_allclose(g[0], [2, 2, 0, 0])
    np.testing.assert_allclose(g[1], [3, 3, 1, 1])

    # fancy indexing: duplicate rows accumulate
    y = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    y.attach_grad()
    idx = nd.array(np.array([0, 2, 2]), dtype="int32")
    with autograd.record():
        l2 = y[idx].sum()
    l2.backward()
    np.testing.assert_allclose(y.grad.asnumpy()[:, 0], [1, 0, 2, 0])

    # views created OUTSIDE record still alias (unchanged semantics)
    z = nd.zeros((4,))
    v = z[1:3]
    z[1:3] = 5
    np.testing.assert_allclose(v.asnumpy(), [5, 5])


def test_copy_and_copyto_on_tape():
    """copy()/copyto() under record() are recorded ops with identity
    gradient (reference: _copyto), not silent tape detachments."""
    x = nd.array(np.ones((2, 3), np.float32))
    x.attach_grad()
    with autograd.record():
        loss = (x.copy() * 3.0).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 3.0 * np.ones((2, 3)))

    y = nd.array(np.ones((2, 3), np.float32))
    y.attach_grad()
    dst = nd.zeros((2, 3))
    with autograd.record():
        out = y.copyto(dst)
        loss = (out * 2.0).sum()
    loss.backward()
    np.testing.assert_allclose(y.grad.asnumpy(), 2.0 * np.ones((2, 3)))
    np.testing.assert_allclose(dst.asnumpy(), 1.0 * np.ones((2, 3)))


def test_copy_on_tape_preserves_dtype():
    m = nd.array(np.array([True, False]))
    with autograd.record():
        c = m.copy()
    assert c.dtype == m.dtype, (c.dtype, m.dtype)


def test_copyto_into_recorded_array_raises():
    """Writing into an array already in the recorded graph must raise
    (reference: 'Assigning to NDArrays that are already in a computational
    graph'), not silently reroute its consumers' gradients."""
    import pytest as _pytest
    from mxnet_tpu.base import MXNetError
    x = nd.array(np.ones((2, 2), np.float32)); x.attach_grad()
    y = nd.array(np.full((2, 2), 7.0, np.float32))
    with autograd.record():
        b = x * 2.0
        c = b + 1.0
        with _pytest.raises(MXNetError):
            y.copyto(b)
    del c


def test_copyto_cross_dtype_on_tape():
    y = nd.array(np.ones((2, 2), np.float32)); y.attach_grad()
    dst = nd.zeros((2, 2), dtype="float64")
    with autograd.record():
        out = y.copyto(dst)
        loss = (out * 2.0).sum()
    loss.backward()
    assert y.grad.dtype == np.float32
    np.testing.assert_allclose(y.grad.asnumpy(), 2.0 * np.ones((2, 2)))
