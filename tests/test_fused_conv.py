"""Pallas fused conv3x3+BN+ReLU kernel (ROOFLINE.md fusion project).

The interpreter-mode run exercises the real kernel on the CPU suite; the
on-chip run (MXNET_TEST_DEVICE=tpu + MXNET_TPU_USE_PALLAS=1) compiles it
for the MXU."""
import os

import numpy as onp
import pytest

import jax.numpy as jnp

import mxnet_tpu  # noqa: F401
from mxnet_tpu.ops import fused_conv as fc


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    # host runs interpret the kernel; the on-chip run compiles it natively
    # for the MXU (round-4 VERDICT weak #2) — and must clear an inherited
    # interpret flag so the native path can't be silently skipped
    from mxnet_tpu.test_utils import is_accel_test_device
    if is_accel_test_device():
        monkeypatch.delenv("MXNET_FLASH_INTERPRET", raising=False)
    else:
        monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
    yield


def _mk(N=2, H=8, W=8, C=16, Cout=16, seed=0, dtype="float32"):
    rng = onp.random.RandomState(seed)
    x = jnp.asarray(rng.randn(N, H, W, C).astype(dtype) * 0.5)
    w = jnp.asarray(rng.randn(3, 3, C, Cout).astype(dtype) * 0.1)
    gamma = jnp.asarray(rng.rand(Cout).astype(dtype) + 0.5)
    beta = jnp.asarray(rng.randn(Cout).astype(dtype) * 0.1)
    mean = jnp.asarray(rng.randn(Cout).astype(dtype) * 0.1)
    var = jnp.asarray(rng.rand(Cout).astype(dtype) + 0.5)
    return x, w, gamma, beta, mean, var


@pytest.mark.parametrize("shape", [(2, 8, 8, 16, 16), (1, 14, 14, 32, 64),
                                   (1, 7, 7, 64, 32)])
def test_fused_matches_xla_reference(shape):
    N, H, W, C, Cout = shape
    x, w, g, b, m, v = _mk(N, H, W, C, Cout)
    scale, shift = fc.fold_bn_params(g, b, m, v)
    got = fc._pallas_conv_bn_relu(x, w, scale, shift)
    want = fc._xla_conv_bn_relu(x, w, scale, shift)
    onp.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_fused_with_residual():
    x, w, g, b, m, v = _mk(2, 8, 8, 16, 16, seed=3)
    res = jnp.asarray(onp.random.RandomState(4)
                      .randn(2, 8, 8, 16).astype("float32"))
    scale, shift = fc.fold_bn_params(g, b, m, v)
    got = fc._pallas_conv_bn_relu(x, w, scale, shift, residual=res)
    want = fc._xla_conv_bn_relu(x, w, scale, shift, residual=res)
    onp.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)
    # relu actually clamps and residual actually contributes
    assert float(jnp.min(got)) == 0.0
    assert not onp.allclose(got, fc._pallas_conv_bn_relu(x, w, scale,
                                                         shift))


def test_fused_op_dispatch_and_bf16():
    from mxnet_tpu import nd
    x, w, g, b, m, v = _mk(1, 8, 8, 16, 16, seed=5, dtype="float32")
    scale, shift = fc.fold_bn_params(g, b, m, v)
    out = nd.contrib.conv_bn_relu(
        nd.array(onp.asarray(x)), nd.array(onp.asarray(w)),
        nd.array(onp.asarray(scale)), nd.array(onp.asarray(shift)))
    want = fc._xla_conv_bn_relu(x, w, scale, shift)
    onp.testing.assert_allclose(out.asnumpy(), want, atol=2e-4, rtol=1e-4)
    # bf16 stream stays bf16
    xb = x.astype(jnp.bfloat16)
    got16 = fc._pallas_conv_bn_relu(xb, w.astype(jnp.bfloat16),
                                    scale, shift)
    assert got16.dtype == jnp.bfloat16
    onp.testing.assert_allclose(got16.astype(jnp.float32), want, atol=0.15,
                                rtol=0.05)


def test_folded_bn_equals_batchnorm_inference():
    """fold_bn_params must reproduce BatchNorm's inference affine."""
    from mxnet_tpu.ops.registry import get
    x, w, g, b, m, v = _mk(1, 8, 8, 16, 16, seed=7)
    conv = fc._xla_conv_bn_relu(x, w, jnp.ones_like(g), jnp.zeros_like(b))
    # undo relu for comparison: use raw conv via lax
    from jax import lax
    raw = lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    bn = get("BatchNorm").fn(raw, g, b, m, v, eps=1e-3, axis=-1,
                             use_global_stats=True, fix_gamma=False)
    if isinstance(bn, tuple):
        bn = bn[0]
    scale, shift = fc.fold_bn_params(g, b, m, v, eps=1e-3)
    onp.testing.assert_allclose(
        onp.maximum(onp.asarray(bn), 0.0),
        fc._xla_conv_bn_relu(x, w, scale, shift), atol=2e-4, rtol=1e-3)


def test_gluon_fused_block_matches_composed():
    """FusedConvBNReLU.from_layers == Conv2D -> BatchNorm(inference) ->
    relu on the same trained parameters."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.cnn import FusedConvBNReLU

    mx.random.seed(0)
    conv = nn.Conv2D(16, kernel_size=3, padding=1, use_bias=False,
                     layout="NHWC", in_channels=8)
    bn = nn.BatchNorm(axis=-1, in_channels=16)
    conv.initialize(mx.init.Xavier())
    bn.initialize()
    # make BN stats non-trivial
    rng = onp.random.RandomState(1)
    bn.running_mean.set_data(nd.array(rng.randn(16).astype("float32") * 0.1))
    bn.running_var.set_data(nd.array(rng.rand(16).astype("float32") + 0.5))
    bn.gamma.set_data(nd.array(rng.rand(16).astype("float32") + 0.5))
    bn.beta.set_data(nd.array(rng.randn(16).astype("float32") * 0.1))

    x = nd.array(rng.randn(2, 8, 8, 8).astype("float32"))
    composed = nd.relu(bn(conv(x)))          # inference mode: global stats
    fused = FusedConvBNReLU.from_layers(conv, bn)
    got = fused(x)
    onp.testing.assert_allclose(got.asnumpy(), composed.asnumpy(),
                                atol=2e-4, rtol=1e-3)
