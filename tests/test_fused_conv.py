"""Pallas fused conv3x3+BN+ReLU kernel (ROOFLINE.md fusion project).

The interpreter-mode run exercises the real kernel on the CPU suite; the
on-chip run (MXNET_TEST_DEVICE=tpu + MXNET_TPU_USE_PALLAS=1) compiles it
for the MXU."""
import os

import numpy as onp
import pytest

import jax.numpy as jnp

import mxnet_tpu  # noqa: F401
from mxnet_tpu import telemetry
from mxnet_tpu.ops import fused_conv as fc

# kernel parity through the interpreter on the CPU backend (this container
# has no chip): interpreter numbers are PARITY evidence only, never perf
# evidence — the interpreter serializes the grid
pytestmark = pytest.mark.pallas


@pytest.fixture(autouse=True)
def _interpret_mode(monkeypatch):
    # host runs interpret the kernel; the on-chip run compiles it natively
    # for the MXU (round-4 VERDICT weak #2) — and must clear an inherited
    # interpret flag so the native path can't be silently skipped
    from mxnet_tpu.test_utils import is_accel_test_device
    if is_accel_test_device():
        monkeypatch.delenv("MXNET_FLASH_INTERPRET", raising=False)
    else:
        monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
    yield


def _mk(N=2, H=8, W=8, C=16, Cout=16, seed=0, dtype="float32"):
    rng = onp.random.RandomState(seed)
    x = jnp.asarray(rng.randn(N, H, W, C).astype(dtype) * 0.5)
    w = jnp.asarray(rng.randn(3, 3, C, Cout).astype(dtype) * 0.1)
    gamma = jnp.asarray(rng.rand(Cout).astype(dtype) + 0.5)
    beta = jnp.asarray(rng.randn(Cout).astype(dtype) * 0.1)
    mean = jnp.asarray(rng.randn(Cout).astype(dtype) * 0.1)
    var = jnp.asarray(rng.rand(Cout).astype(dtype) + 0.5)
    return x, w, gamma, beta, mean, var


@pytest.mark.parametrize("shape", [(2, 8, 8, 16, 16), (1, 14, 14, 32, 64),
                                   (1, 7, 7, 64, 32)])
def test_fused_matches_xla_reference(shape):
    N, H, W, C, Cout = shape
    x, w, g, b, m, v = _mk(N, H, W, C, Cout)
    scale, shift = fc.fold_bn_params(g, b, m, v)
    got = fc._pallas_conv_bn_relu(x, w, scale, shift)
    want = fc._xla_conv_bn_relu(x, w, scale, shift)
    onp.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


def test_fused_with_residual():
    x, w, g, b, m, v = _mk(2, 8, 8, 16, 16, seed=3)
    res = jnp.asarray(onp.random.RandomState(4)
                      .randn(2, 8, 8, 16).astype("float32"))
    scale, shift = fc.fold_bn_params(g, b, m, v)
    got = fc._pallas_conv_bn_relu(x, w, scale, shift, residual=res)
    want = fc._xla_conv_bn_relu(x, w, scale, shift, residual=res)
    onp.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)
    # relu actually clamps and residual actually contributes
    assert float(jnp.min(got)) == 0.0
    assert not onp.allclose(got, fc._pallas_conv_bn_relu(x, w, scale,
                                                         shift))


def test_fused_op_dispatch_and_bf16():
    from mxnet_tpu import nd
    x, w, g, b, m, v = _mk(1, 8, 8, 16, 16, seed=5, dtype="float32")
    scale, shift = fc.fold_bn_params(g, b, m, v)
    out = nd.contrib.conv_bn_relu(
        nd.array(onp.asarray(x)), nd.array(onp.asarray(w)),
        nd.array(onp.asarray(scale)), nd.array(onp.asarray(shift)))
    want = fc._xla_conv_bn_relu(x, w, scale, shift)
    onp.testing.assert_allclose(out.asnumpy(), want, atol=2e-4, rtol=1e-4)
    # bf16 stream stays bf16
    xb = x.astype(jnp.bfloat16)
    got16 = fc._pallas_conv_bn_relu(xb, w.astype(jnp.bfloat16),
                                    scale, shift)
    assert got16.dtype == jnp.bfloat16
    onp.testing.assert_allclose(got16.astype(jnp.float32), want, atol=0.15,
                                rtol=0.05)


def test_folded_bn_equals_batchnorm_inference():
    """fold_bn_params must reproduce BatchNorm's inference affine."""
    from mxnet_tpu.ops.registry import get
    x, w, g, b, m, v = _mk(1, 8, 8, 16, 16, seed=7)
    conv = fc._xla_conv_bn_relu(x, w, jnp.ones_like(g), jnp.zeros_like(b))
    # undo relu for comparison: use raw conv via lax
    from jax import lax
    raw = lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    bn = get("BatchNorm").fn(raw, g, b, m, v, eps=1e-3, axis=-1,
                             use_global_stats=True, fix_gamma=False)
    if isinstance(bn, tuple):
        bn = bn[0]
    scale, shift = fc.fold_bn_params(g, b, m, v, eps=1e-3)
    onp.testing.assert_allclose(
        onp.maximum(onp.asarray(bn), 0.0),
        fc._xla_conv_bn_relu(x, w, scale, shift), atol=2e-4, rtol=1e-3)


def test_gluon_fused_block_matches_composed():
    """FusedConvBNReLU.from_layers == Conv2D -> BatchNorm(inference) ->
    relu on the same trained parameters."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.cnn import FusedConvBNReLU

    mx.random.seed(0)
    conv = nn.Conv2D(16, kernel_size=3, padding=1, use_bias=False,
                     layout="NHWC", in_channels=8)
    bn = nn.BatchNorm(axis=-1, in_channels=16)
    conv.initialize(mx.init.Xavier())
    bn.initialize()
    # make BN stats non-trivial
    rng = onp.random.RandomState(1)
    bn.running_mean.set_data(nd.array(rng.randn(16).astype("float32") * 0.1))
    bn.running_var.set_data(nd.array(rng.rand(16).astype("float32") + 0.5))
    bn.gamma.set_data(nd.array(rng.rand(16).astype("float32") + 0.5))
    bn.beta.set_data(nd.array(rng.randn(16).astype("float32") * 0.1))

    x = nd.array(rng.randn(2, 8, 8, 8).astype("float32"))
    composed = nd.relu(bn(conv(x)))          # inference mode: global stats
    fused = FusedConvBNReLU.from_layers(conv, bn)
    got = fused(x)
    onp.testing.assert_allclose(got.asnumpy(), composed.asnumpy(),
                                atol=2e-4, rtol=1e-3)


# ---------------------------------------------------------------------------
# TRAINING-form fusion (round-5: conv + batch-stats epilogue + normalize,
# backward included)
# ---------------------------------------------------------------------------
def _composed_train_ref(x, w, gamma, beta, residual=None, eps=1e-3):
    """Plain-jax composed reference: conv -> batch stats -> norm -> relu."""
    import jax
    from jax import lax
    conv = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    mean = jnp.mean(conv, axis=(0, 1, 2))
    var = jnp.var(conv, axis=(0, 1, 2))
    xhat = (conv - mean) / jnp.sqrt(var + eps)
    y = xhat * gamma + beta
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    return jnp.maximum(y, 0.0).astype(x.dtype), mean, var


@pytest.mark.parametrize("shape,res", [((2, 8, 8, 16, 16), False),
                                       ((1, 14, 14, 32, 64), False),
                                       ((2, 8, 8, 16, 16), True)])
def test_train_forward_matches_composed(shape, res):
    N, H, W, C, Cout = shape
    x, w, g, b, _, _ = _mk(N, H, W, C, Cout, seed=7)
    residual = (jnp.asarray(onp.random.RandomState(8)
                            .randn(N, H, W, Cout).astype("float32") * 0.1)
                if res else None)
    out, mean, var = fc._cbr_train(1e-3, res, x, w, g, b, residual)
    wout, wmean, wvar = _composed_train_ref(x, w, g, b, residual)
    onp.testing.assert_allclose(mean, wmean, atol=1e-4, rtol=1e-4)
    onp.testing.assert_allclose(var, wvar, atol=1e-4, rtol=1e-4)
    onp.testing.assert_allclose(out, wout, atol=5e-4, rtol=1e-3)


def test_train_pallas_stats_match_xla():
    x, w, g, b, _, _ = _mk(2, 8, 8, 16, 32, seed=9)
    co_p, s_p, sq_p = fc._pallas_conv_stats(x, w)
    co_x, s_x, sq_x = fc._xla_conv_stats(x, w)
    onp.testing.assert_allclose(co_p, co_x, atol=2e-4, rtol=1e-4)
    onp.testing.assert_allclose(s_p, s_x, atol=2e-3, rtol=1e-4)
    onp.testing.assert_allclose(sq_p, sq_x, atol=2e-3, rtol=1e-4)


@pytest.mark.parametrize("res", [False, True])
def test_train_backward_matches_composed(res):
    import jax
    N, H, W, C, Cout = 2, 8, 8, 16, 16
    x, w, g, b, _, _ = _mk(N, H, W, C, Cout, seed=11)
    residual = (jnp.asarray(onp.random.RandomState(12)
                            .randn(N, H, W, Cout).astype("float32") * 0.1)
                if res else None)
    cot = jnp.asarray(onp.random.RandomState(13)
                      .rand(N, H, W, Cout).astype("float32"))

    def loss_fused(x_, w_, g_, b_, r_):
        out, _, _ = fc._cbr_train(1e-3, res, x_, w_, g_, b_, r_)
        return jnp.sum(out * cot)

    def loss_ref(x_, w_, g_, b_, r_):
        out, _, _ = _composed_train_ref(x_, w_, g_, b_, r_)
        return jnp.sum(out * cot)

    n = 5 if res else 4
    argnums = tuple(range(n))
    got = jax.grad(loss_fused, argnums=argnums)(x, w, g, b, residual)
    want = jax.grad(loss_ref, argnums=argnums)(x, w, g, b, residual)
    names = ["dx", "dw", "dgamma", "dbeta", "dres"]
    for gg, ww, nm in zip(got, want, names):
        onp.testing.assert_allclose(gg, ww, atol=2e-3, rtol=2e-3,
                                    err_msg=nm)


@pytest.mark.parametrize("res", [False, True])
def test_pallas_bwd_matches_xla_epilogue(res):
    """ISSUE 10 tentpole: the single-pallas_call backward (`_pallas_cbr_bwd`,
    phase-grid: reductions then dconv/dres) against the composite XLA
    epilogue on the same saved tensors. Interpreter run on the CPU backend
    — parity evidence only, not perf evidence. Reduction association
    differs (per-image accumulate vs whole-tensor reduce), so parity is
    fp32-round-off, not bitwise."""
    N, H, W, C, Cout = 2, 8, 8, 16, 32
    x, w, g, b, _, _ = _mk(N, H, W, C, Cout, seed=17)
    out, mean, var, invstd, conv_out = fc._cbr_train_compute(
        1e-3, x, w, g, b, None)
    rng = onp.random.RandomState(18)
    dy = jnp.asarray(rng.randn(N, H, W, Cout).astype("float32"))
    residual = (jnp.asarray(rng.randn(N, H, W, Cout).astype("float32"))
                if res else None)
    got = fc._pallas_cbr_bwd(conv_out, dy, mean, invstd, g, b, residual)
    want = fc._xla_cbr_bwd(conv_out, dy, mean, invstd, g, b, residual)
    names = ["dconv", "dgamma", "dbeta", "dres"]
    for a, e, nm in zip(got, want, names):
        if e is None:
            assert a is None, nm
            continue
        onp.testing.assert_allclose(a, e, atol=2e-4, rtol=2e-5, err_msg=nm)


def test_bwd_dispatch_and_fallback_counters():
    """Every Pallas dispatch/fallback is visible in telemetry: a good-shape
    backward counts ops.pallas.dispatch.cbr_train_bwd; a shape the kernel
    cannot tile (C not a multiple of 8) counts a fallback REASON and still
    produces gradients through the XLA composite."""
    import jax

    def counters():
        return dict(telemetry.snapshot()["counters"])

    def grad_of(C):
        x, w, g, b, _, _ = _mk(1, 8, 8, C, C, seed=19)

        def loss(x_, w_, g_, b_):
            out, _, _ = fc._cbr_train(1e-3, False, x_, w_, g_, b_, None)
            return jnp.sum(out)
        return jax.grad(loss, argnums=(1,))(x, w, g, b)

    before = counters()
    grad_of(16)
    mid = counters()
    assert mid.get("ops.pallas.dispatch.cbr_train_bwd", 0) > \
        before.get("ops.pallas.dispatch.cbr_train_bwd", 0)
    assert mid.get("ops.pallas.dispatch.cbr_train_fwd", 0) > \
        before.get("ops.pallas.dispatch.cbr_train_fwd", 0)
    (dw,) = grad_of(12)   # 12 % 8 != 0 -> counted fallback, never an error
    after = counters()
    assert after.get("ops.pallas.fallback.cbr_train_bwd.shape", 0) > \
        mid.get("ops.pallas.fallback.cbr_train_bwd.shape", 0)
    assert onp.isfinite(onp.asarray(dw)).all()


def test_train_op_through_registry_tape():
    """The registered op through invoke + the imperative tape."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd
    from mxnet_tpu.ndarray.ndarray import invoke
    rng = onp.random.RandomState(21)
    x = nd.array(rng.randn(2, 8, 8, 16).astype("float32") * 0.5)
    w = nd.array(rng.randn(3, 3, 16, 16).astype("float32") * 0.1)
    g = nd.array(rng.rand(16).astype("float32") + 0.5)
    b = nd.array(rng.randn(16).astype("float32") * 0.1)
    for t in (x, w, g, b):
        t.attach_grad()
    with autograd.record():
        out, mean, var = invoke("_contrib_conv_bn_relu_train", x, w, g, b)
        loss = out.sum()
    loss.backward()
    for t, nm in ((x, "x"), (w, "w"), (g, "gamma"), (b, "beta")):
        assert t.grad is not None, nm
        arr = t.grad.asnumpy()
        assert onp.isfinite(arr).all() and onp.abs(arr).max() > 0, nm
    # batch stats are usable for running-stat updates
    assert float(var.asnumpy().min()) >= 0.0


def test_gluon_train_block_matches_composed_chain():
    """FusedConvBNReLUTrain == Conv2D(NHWC) -> BatchNorm -> relu, both in
    training mode (forward, grads, running-stat update) and in eval."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.cnn import FusedConvBNReLUTrain

    rng = onp.random.RandomState(31)
    C = 16
    xb = nd.array(rng.randn(2, 8, 8, C).astype("float32") * 0.5)

    mx.random.seed(5)
    fused = FusedConvBNReLUTrain(C, in_channels=C, epsilon=1e-5)
    fused.initialize(mx.init.Xavier())

    conv = nn.Conv2D(C, 3, padding=1, layout="NHWC", use_bias=False,
                     in_channels=C)
    bn = nn.BatchNorm(axis=3, in_channels=C, epsilon=1e-5)
    conv.initialize(mx.init.Xavier())
    bn.initialize()
    # share the conv weight: Conv2D NHWC keeps (Cout, kh, kw, Cin)
    w_hwio = fused.weight.data().data_jax
    conv.weight.set_data(nd.array(onp.transpose(
        onp.asarray(w_hwio), (3, 0, 1, 2))))

    with autograd.record():
        y_f = fused(xb)
        lf = y_f.sum()
    lf.backward()
    gw_f = fused.weight.grad().asnumpy()
    rm_f = fused.running_mean.data().asnumpy()

    with autograd.record():
        y_c = nd.relu(bn(conv(xb)))
        lc = y_c.sum()
    lc.backward()
    gw_c = conv.weight.grad().asnumpy()
    rm_c = bn.running_mean.data().asnumpy()

    onp.testing.assert_allclose(y_f.asnumpy(), y_c.asnumpy(), atol=5e-4,
                                rtol=1e-3)
    onp.testing.assert_allclose(gw_f, onp.transpose(gw_c, (1, 2, 3, 0)),
                                atol=2e-3, rtol=2e-3)
    onp.testing.assert_allclose(rm_f, rm_c, atol=1e-5, rtol=1e-4)

    # eval mode: folded path vs composed eval path
    y_fe = fused(xb)
    y_ce = nd.relu(bn(conv(xb)))
    onp.testing.assert_allclose(y_fe.asnumpy(), y_ce.asnumpy(), atol=5e-4,
                                rtol=2e-3)


def test_zoo_resnet50_fused_convbn_gate(monkeypatch):
    """MXNET_TPU_FUSED_CONVBN=1 + layout=NHWC swaps every bottleneck's
    interior conv3x3+BN+relu for FusedConvBNReLUTrain; the model still
    builds, trains one step, and updates running stats."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.contrib.cnn import FusedConvBNReLUTrain

    monkeypatch.setenv("MXNET_TPU_FUSED_CONVBN", "1")
    mx.random.seed(0)
    net = vision.resnet50_v1(classes=10, layout="NHWC")
    fused_blocks = [b for b in net.collect_params().keys()
                    if "fusedconvbnrelutrain" in b.lower()]
    # resnet50 has 16 bottlenecks -> 16 fused interior convs
    blocks = []

    def walk(blk):
        for c in blk._children.values():
            if isinstance(c, FusedConvBNReLUTrain):
                blocks.append(c)
            walk(c)
    walk(net)
    assert len(blocks) == 16, "expected 16 fused bottleneck interiors, " \
        "found %d" % len(blocks)

    net.initialize(mx.init.Xavier())
    rng = onp.random.RandomState(1)
    x = nd.array(rng.randn(2, 32, 32, 3).astype("float32"))
    y = nd.array(rng.randint(0, 10, (2,)).astype("float32"))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.01})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        loss = sce(net(x), y).mean()
    loss.backward()
    tr.step(2)
    assert onp.isfinite(loss.asnumpy()).all()
    rm = blocks[0].running_mean.data().asnumpy()
    assert onp.abs(rm).max() > 0, "fused block never updated running stats"
    # eval path (folded kernel) still runs
    out = net(x)
    assert out.shape == (2, 10)


def test_zoo_resnet50_gate_off_unchanged(monkeypatch):
    """Without the gate the zoo model keeps the composed triple (param
    names stay checkpoint-compatible)."""
    from mxnet_tpu.gluon.model_zoo import vision
    monkeypatch.delenv("MXNET_TPU_FUSED_CONVBN", raising=False)
    net = vision.resnet50_v1(classes=10, layout="NHWC")
    names = " ".join(net.collect_params().keys())
    assert "fusedconvbnrelutrain" not in names.lower()
