"""Tests for mxnet_tpu.parallel on the 8-device CPU mesh (conftest forces
JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8 — the SURVEY.md §4
local-launcher analog for distributed tests without a cluster)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from mxnet_tpu import parallel as par


def test_mesh_creation():
    mesh = par.create_mesh(data=4, model=2)
    assert mesh.devices.size == 8
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["data"] == 4
    assert par.current_mesh() is None
    with par.mesh_scope(mesh) as m:
        assert par.current_mesh() is m
    assert par.current_mesh() is None


def test_local_and_auto_mesh():
    m = par.local_mesh(4)
    assert m.devices.size == 4
    m2 = par.auto_mesh(model_parallel=2)
    sizes = dict(zip(m2.axis_names, m2.devices.shape))
    assert sizes["model"] == 2 and sizes["data"] == 4


def test_sharding_rules_prune():
    mesh = par.create_mesh(data=8)  # no real model axis
    spec = par.LLAMA_RULES.spec_for("layers/0/attn/wq", (256, 512), mesh)
    # model axis has size 1 → pruned; fsdp size 1 → pruned
    assert spec == P()
    mesh2 = par.create_mesh(data=2, model=4)
    spec2 = par.LLAMA_RULES.spec_for("layers/0/attn/wq", (256, 512), mesh2)
    assert spec2 == P(None, "model")
    # non-divisible dim drops the axis rather than erroring
    spec3 = par.LLAMA_RULES.spec_for("layers/0/attn/wq", (256, 510), mesh2)
    assert spec3 == P()


def test_shard_pytree_places_params():
    mesh = par.create_mesh(data=2, model=4)
    params = {"layers": {"0": {"attn": {"wq": jnp.ones((16, 8)),
                                        "wo": jnp.ones((8, 16))}}},
              "norm": jnp.ones((16,))}
    sharded = par.shard_pytree(params, par.LLAMA_RULES, mesh)
    wq = sharded["layers"]["0"]["attn"]["wq"]
    assert wq.sharding.spec == P(None, "model")
    assert sharded["norm"].sharding.spec == P()


def test_collectives_inside_shard_map():
    mesh = par.local_mesh(8, axis="data")
    x = jax.device_put(jnp.arange(8.0), NamedSharding(mesh, P("data")))

    def f(t):
        s = par.all_reduce(t, "data")
        g = par.all_gather(t, "data")
        return s, g

    sfn = shard_map(f, mesh=mesh, in_specs=P("data"),
                    out_specs=(P(), P("data")))
    s, g = jax.jit(sfn)(x)
    assert float(s[0]) == float(jnp.sum(jnp.arange(8.0)))
    np.testing.assert_allclose(np.asarray(g)[:8], np.arange(8.0))


def test_barrier_and_bench_smoke():
    mesh = par.local_mesh(8)
    par.barrier(mesh)
    gbps, dt = par.allreduce_bench(size_mb=1, iters=2, mesh=mesh)
    assert gbps > 0 and dt > 0


def test_dist_single_process():
    par.initialize()
    assert par.is_initialized()
    assert par.rank() == 0
    assert par.num_workers() == 1


def _np_attention(q, k, v, causal=False):
    H, Hkv = q.shape[1], k.shape[1]
    if Hkv != H:
        k = np.repeat(k, H // Hkv, axis=1)
        v = np.repeat(v, H // Hkv, axis=1)
    scale = q.shape[-1] ** -0.5
    s = np.einsum("bhqd,bhkd->bhqk", q.astype(np.float64),
                  k.astype(np.float64)) * scale
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        qi = np.arange(Sq)[:, None] + (Sk - Sq)
        ki = np.arange(Sk)[None, :]
        s = np.where(ki <= qi, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v.astype(np.float64))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_fallback(causal):
    rng = np.random.RandomState(0)
    q = rng.randn(2, 4, 64, 32).astype(np.float32)
    k = rng.randn(2, 2, 64, 32).astype(np.float32)  # GQA 2 kv heads
    v = rng.randn(2, 2, 64, 32).astype(np.float32)
    out = par.flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=causal)
    ref = _np_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_flash_attention_grad():
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))

    def loss(q, k, v):
        return jnp.sum(par.flash_attention(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def ref_loss(q, k, v):
        from mxnet_tpu.parallel.flash_attention import _ref_attention
        return jnp.sum(_ref_attention(q, k, v, True, 8 ** -0.5) ** 2)

    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    mesh = par.local_mesh(4, axis="seq")
    rng = np.random.RandomState(2)
    B, H, S, D = 2, 2, 32, 16
    q = rng.randn(B, H, S, D).astype(np.float32)
    k = rng.randn(B, H, S, D).astype(np.float32)
    v = rng.randn(B, H, S, D).astype(np.float32)

    f = shard_map(
        lambda q_, k_, v_: par.ring_attention(q_, k_, v_, axis_name="seq",
                                              causal=causal),
        mesh=mesh, in_specs=P(None, None, "seq", None),
        out_specs=P(None, None, "seq", None))
    out = jax.jit(f)(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    ref = _np_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gqa_matches_full(causal):
    """Hkv < H: the grouped-einsum GQA path (no K/V repeat on the ring)."""
    mesh = par.local_mesh(4, axis="seq")
    rng = np.random.RandomState(7)
    B, H, Hkv, S, D = 2, 4, 2, 32, 16
    q = rng.randn(B, H, S, D).astype(np.float32)
    k = rng.randn(B, Hkv, S, D).astype(np.float32)
    v = rng.randn(B, Hkv, S, D).astype(np.float32)

    f = shard_map(
        lambda q_, k_, v_: par.ring_attention(q_, k_, v_, axis_name="seq",
                                              causal=causal),
        mesh=mesh, in_specs=P(None, None, "seq", None),
        out_specs=P(None, None, "seq", None))
    out = jax.jit(f)(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    ref = _np_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_sharded_train_step_linear_regression():
    mesh = par.create_mesh(data=2, model=4)
    rng = np.random.RandomState(3)
    w_true = rng.randn(8, 4).astype(np.float32)
    params = {"mlp": {"w1": jnp.zeros((8, 4))}}  # matched by LLAMA mlp rule

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["mlp"]["w1"]
        return jnp.mean((pred - y) ** 2)

    step = par.ShardedTrainStep(loss_fn, params, mesh,
                                rules=par.LLAMA_RULES, optimizer="adam",
                                lr=0.1)
    p, s = step.init()
    assert p["mlp"]["w1"].sharding.spec == P(None, "model")
    losses = []
    for i in range(60):
        x = rng.randn(16, 8).astype(np.float32)
        y = x @ w_true
        p, s, loss = step(p, s, (jnp.asarray(x), jnp.asarray(y)), i)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.05


def test_sharded_train_step_grad_accum():
    mesh = par.local_mesh(2, axis="data")
    params = {"w": jnp.zeros((4,))}

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"][:, None] - y) ** 2)

    step = par.ShardedTrainStep(loss_fn, params, mesh, optimizer="sgd",
                                lr=0.05, grad_accum=2, momentum=0.9)
    p, s = step.init()
    rng = np.random.RandomState(4)
    w_true = rng.randn(4).astype(np.float32)
    for i in range(150):
        x = rng.randn(8, 4).astype(np.float32)
        y = (x @ w_true)[:, None]
        p, s, loss = step(p, s, (jnp.asarray(x), jnp.asarray(y)), i)
    np.testing.assert_allclose(np.asarray(p["w"]), w_true, atol=0.05)


# ---------------------------------------------------------------------------
# elastic place() buffer donation (resilience-v2 follow-on: grow-back
# re-layout must peak at max(old, new) + one leaf, not old + new)
# ---------------------------------------------------------------------------
def test_reshard_pytree_donate_deletes_sources():
    from mxnet_tpu.parallel.sharding import LLAMA_RULES, reshard_pytree
    mesh = par.local_mesh(4, axis="data")
    params = {"layers": {"0": {"mlp": {"w1": jnp.ones((8, 16))}}},
              "norm": jnp.arange(8.0)}
    sources = jax.tree_util.tree_leaves(params)
    expect = [np.asarray(x) for x in sources]
    out = reshard_pytree(params, LLAMA_RULES, mesh, donate=True)
    assert all(x.is_deleted() for x in sources)
    for got, want in zip(jax.tree_util.tree_leaves(out), expect):
        np.testing.assert_array_equal(np.asarray(got), want)
    # default stays non-destructive
    params2 = {"w": jnp.arange(6.0)}
    src2 = jax.tree_util.tree_leaves(params2)
    reshard_pytree(params2, LLAMA_RULES, mesh)
    assert not any(x.is_deleted() for x in src2)


def test_place_donates_and_step_continues():
    """place() consumes its inputs by default (the relayout adapters drop
    them immediately); the re-laid state must be bit-identical and the
    rebuilt step must run on it."""
    mesh = par.local_mesh(2, axis="data")

    def loss_fn(params, batch):
        return jnp.mean((batch @ params["w"]) ** 2)

    step = par.ShardedTrainStep(loss_fn, {"w": jnp.ones((4,))}, mesh,
                                optimizer="adam", lr=0.01)
    p, s = step.init()
    p, s, _ = step(p, s, jnp.ones((4, 4)), 0)
    expect_w = np.asarray(p["w"])
    old_leaves = jax.tree_util.tree_leaves((p, s))
    rebuilt = step.rebuild_for_mesh(par.local_mesh(4, axis="data"))
    p2, s2 = rebuilt.place(p, s)
    assert all(x.is_deleted() for x in old_leaves)
    np.testing.assert_array_equal(np.asarray(p2["w"]), expect_w)
    # optimizer-state scalars survived the donated move
    assert int(s2["t"]) == 1
    p3, s3, loss = rebuilt(p2, s2, jnp.ones((8, 4)), 1)
    assert np.isfinite(float(loss))
    # opt-out keeps sources alive (A/B comparisons)
    keep = jax.tree_util.tree_leaves((p3, s3))
    rebuilt.place(p3, s3, donate=False)
    assert not any(x.is_deleted() for x in keep)
