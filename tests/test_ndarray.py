"""NDArray basics (reference suite: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), [[1, 2], [3, 4]])

    z = nd.zeros((3, 4))
    assert z.shape == (3, 4) and z.asnumpy().sum() == 0
    o = nd.ones((2, 3), dtype="int32")
    assert o.dtype == np.int32
    f = nd.full((2, 2), 7.0)
    assert np.all(f.asnumpy() == 7)
    r = nd.arange(0, 10, 2)
    assert np.allclose(r.asnumpy(), [0, 2, 4, 6, 8])


def test_default_float32():
    a = nd.array(np.zeros((2, 2), dtype=np.float64))
    assert a.dtype == np.float32  # MXNet's default-dtype semantics


def test_arith():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([4.0, 5.0, 6.0])
    assert np.allclose((a + b).asnumpy(), [5, 7, 9])
    assert np.allclose((a - b).asnumpy(), [-3, -3, -3])
    assert np.allclose((a * b).asnumpy(), [4, 10, 18])
    assert np.allclose((b / a).asnumpy(), [4, 2.5, 2])
    assert np.allclose((a + 1).asnumpy(), [2, 3, 4])
    assert np.allclose((2 * a).asnumpy(), [2, 4, 6])
    assert np.allclose((1 - a).asnumpy(), [0, -1, -2])
    assert np.allclose((a ** 2).asnumpy(), [1, 4, 9])
    assert np.allclose((-a).asnumpy(), [-1, -2, -3])


def test_inplace_arith():
    a = nd.ones((2, 2))
    a += 1
    assert np.all(a.asnumpy() == 2)
    a *= 3
    assert np.all(a.asnumpy() == 6)
    a /= 2
    assert np.all(a.asnumpy() == 3)
    a -= 1
    assert np.all(a.asnumpy() == 2)


def test_view_aliasing():
    """Writes through base and view must be mutually visible (reference:
    zero-copy NDArray::Slice)."""
    a = nd.zeros((4, 4))
    v = a[1:3]
    a[1:3] = 5.0
    assert np.all(v.asnumpy() == 5.0)
    v[:] = 7.0
    assert np.all(a.asnumpy()[1:3] == 7.0)
    assert np.all(a.asnumpy()[0] == 0.0)
    # view of view
    vv = v[0]
    vv[:] = 9.0
    assert np.all(a.asnumpy()[1] == 9.0)


def test_setitem_getitem():
    a = nd.zeros((3, 3))
    a[1, 2] = 4.0
    assert a.asnumpy()[1, 2] == 4.0
    a[:] = 1.0
    assert np.all(a.asnumpy() == 1.0)
    b = a[2]
    assert b.shape == (3,)
    idx = nd.array([0, 2], dtype="int32")
    picked = a[idx]          # advanced indexing → copy
    assert picked.shape == (2, 3)


def test_reshape_transpose():
    a = nd.arange(0, 12).reshape((3, 4))
    assert a.shape == (3, 4)
    assert a.T.shape == (4, 3)
    assert a.reshape((2, 6)).shape == (2, 6)
    assert a.reshape((-1,)).shape == (12,)
    assert a.reshape((0, -1)).shape == (3, 4)   # MXNet reshape code 0
    assert a.reshape((-3,)).shape == (12,)      # merge two dims
    assert nd.transpose(a, axes=(1, 0)).shape == (4, 3)
    assert a.flatten().shape == (3, 4)
    assert a.expand_dims(0).shape == (1, 3, 4)
    assert a.reshape((3, 4, 1)).squeeze(axis=2).shape == (3, 4)


def test_reductions():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert a.sum().asscalar() == 10
    assert np.allclose(a.sum(axis=0).asnumpy(), [4, 6])
    assert np.allclose(a.mean(axis=1).asnumpy(), [1.5, 3.5])
    assert a.max().asscalar() == 4
    assert a.min().asscalar() == 1
    assert a.prod().asscalar() == 24
    assert np.allclose(nd.sum(a, axis=0, exclude=True).asnumpy(), [3, 7])
    assert a.argmax(axis=1).asnumpy().tolist() == [1, 1]
    assert a.norm().asscalar() == pytest.approx(np.sqrt(30), rel=1e-5)


def test_dot():
    a = nd.array(np.random.rand(3, 4))
    b = nd.array(np.random.rand(4, 5))
    c = nd.dot(a, b)
    assert c.shape == (3, 5)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)
    # transpose flags
    d = nd.dot(a, b.T, transpose_b=True)
    assert np.allclose(d.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)
    # batch_dot
    x = nd.array(np.random.rand(2, 3, 4))
    y = nd.array(np.random.rand(2, 4, 5))
    z = nd.batch_dot(x, y)
    assert z.shape == (2, 3, 5)


def test_concat_stack_split():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)
    parts = nd.split(c, num_outputs=2, axis=0)
    assert len(parts) == 2 and parts[0].shape == (2, 3)


def test_comparison_where_clip():
    a = nd.array([1.0, 5.0, 3.0])
    b = nd.array([2.0, 2.0, 3.0])
    assert (a > b).asnumpy().tolist() == [0, 1, 0]
    assert (a == b).asnumpy().tolist() == [0, 0, 1]
    w = nd.where(a > b, a, b)
    assert w.asnumpy().tolist() == [2, 5, 3]
    assert a.clip(2, 4).asnumpy().tolist() == [2, 4, 3]


def test_copy_context():
    a = nd.ones((2, 2), ctx=mx.cpu())
    b = a.copy()
    b[:] = 3
    assert np.all(a.asnumpy() == 1)
    c = nd.zeros((2, 2))
    a.copyto(c)
    assert np.all(c.asnumpy() == 1)
    d = a.as_in_context(mx.cpu())
    assert d is a
    assert a.context.device_type == "cpu"


def test_astype():
    a = nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    assert a.astype(np.float16).dtype == np.float16


def test_take_pick_onehot():
    a = nd.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    t = nd.take(a, nd.array([0, 2], dtype="int32"))
    assert np.allclose(t.asnumpy(), [[1, 2], [5, 6]])
    p = nd.pick(a, nd.array([0, 1, 0]), axis=1)
    assert p.asnumpy().tolist() == [1, 4, 5]
    oh = nd.one_hot(nd.array([0, 2], dtype="int32"), 3)
    assert np.allclose(oh.asnumpy(), [[1, 0, 0], [0, 0, 1]])


def test_wait_and_sync():
    a = nd.ones((100, 100))
    b = nd.dot(a, a)
    b.wait_to_read()
    nd.waitall()
    assert b.asnumpy()[0, 0] == 100


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == 3.5
    assert int(nd.array([7], dtype="int32").asscalar()) == 7
    with pytest.raises(ValueError):
        nd.ones((2, 2)).asscalar()


def test_topk_sort():
    a = nd.array([[3.0, 1.0, 2.0]])
    idx = nd.topk(a, k=2)
    assert idx.asnumpy()[0].tolist() == [0, 2]
    both = nd.topk(a, k=2, ret_typ="both")
    assert np.allclose(both[0].asnumpy()[0], [3, 2])
    assert nd.sort(a).asnumpy()[0].tolist() == [1, 2, 3]
    assert nd.argsort(a).asnumpy()[0].tolist() == [1, 2, 0]


def test_elemwise_unary():
    a = nd.array([1.0, 4.0, 9.0])
    assert np.allclose(nd.sqrt(a).asnumpy(), [1, 2, 3])
    assert np.allclose(nd.square(a).asnumpy(), [1, 16, 81])
    assert np.allclose(nd.exp(nd.zeros((2,))).asnumpy(), [1, 1])
    assert np.allclose(nd.log(a).asnumpy(), np.log([1, 4, 9]), atol=1e-6)
    assert np.allclose(nd.relu(nd.array([-1.0, 2.0])).asnumpy(), [0, 2])
    s = nd.sigmoid(nd.zeros((1,)))
    assert s.asnumpy()[0] == pytest.approx(0.5)


def test_broadcasting():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)
    c = nd.broadcast_to(nd.ones((1, 3)), shape=(5, 3))
    assert c.shape == (5, 3)
    d = nd.broadcast_axis(nd.ones((1, 3)), axis=0, size=4)
    assert d.shape == (4, 3)


def test_correlation_op():
    """reference: src/operator/correlation.cc — verified against a direct
    numpy loop for a small case."""
    rng = np.random.RandomState(0)
    n, c, h, w = 2, 3, 8, 8
    d1 = rng.randn(n, c, h, w).astype(np.float32)
    d2 = rng.randn(n, c, h, w).astype(np.float32)
    md, k = 2, 1
    out = nd.invoke("Correlation", nd.array(d1), nd.array(d2),
                    kernel_size=k, max_displacement=md, stride1=1,
                    stride2=1, pad_size=md).asnumpy()
    D = 2 * md + 1
    assert out.shape == (n, D * D, h, w)
    # numpy reference at a few positions
    p1 = np.pad(d1, ((0, 0), (0, 0), (md, md), (md, md)))
    p2 = np.pad(d2, ((0, 0), (0, 0), (md, md), (md, md)))
    for (dy, dx, y, x) in [(0, 0, 3, 3), (-2, 1, 4, 2), (2, -2, 2, 5)]:
        ch = (dy + md) * D + (dx + md)
        a = p1[:, :, y + md, x + md]
        b = p2[:, :, y + md + dy, x + md + dx]
        expect = (a * b).sum(axis=1) / c
        np.testing.assert_allclose(out[:, ch, y, x], expect, rtol=1e-4)
    # abs-difference mode
    out2 = nd.invoke("Correlation", nd.array(d1), nd.array(d2),
                     kernel_size=k, max_displacement=md, stride1=1,
                     stride2=1, pad_size=md, is_multiply=False).asnumpy()
    a = p1[:, :, 3 + md, 3 + md]
    b = p2[:, :, 3 + md, 3 + md]
    np.testing.assert_allclose(out2[:, md * D + md, 3, 3],
                               np.abs(a - b).sum(axis=1) / c, rtol=1e-4)


def test_interleaved_matmul_selfatt_ops():
    """reference: src/operator/contrib/transformer.cc — checked against the
    documented equivalent-code layout."""
    rng = np.random.RandomState(0)
    S, B, H, D = 6, 2, 4, 8
    qkv = rng.randn(S, B, H * 3 * D).astype(np.float32)
    scores = mx.nd.contrib.interleaved_matmul_selfatt_qk(nd.array(qkv),
                                                         heads=H)
    assert scores.shape == (B * H, S, S)
    att = mx.nd.softmax(scores, axis=-1)
    out = mx.nd.contrib.interleaved_matmul_selfatt_valatt(nd.array(qkv), att,
                                                          heads=H)
    assert out.shape == (S, B, H * D)
    t = qkv.reshape(S, B, H, 3, D)
    q = t[:, :, :, 0, :].transpose(1, 2, 0, 3).reshape(B * H, S, D) \
        / np.sqrt(D)
    k = t[:, :, :, 1, :].transpose(1, 2, 0, 3).reshape(B * H, S, D)
    v = t[:, :, :, 2, :].transpose(1, 2, 0, 3).reshape(B * H, S, D)
    sc = np.einsum("bqd,bkd->bqk", q, k)
    np.testing.assert_allclose(scores.asnumpy(), sc, rtol=1e-5)
    e = np.exp(sc - sc.max(-1, keepdims=True))
    a = e / e.sum(-1, keepdims=True)
    o = (np.einsum("bqk,bkd->bqd", a, v).reshape(B, H, S, D)
         .transpose(2, 0, 1, 3).reshape(S, B, H * D))
    np.testing.assert_allclose(out.asnumpy(), o, rtol=1e-4)
    # gradients flow (it backs real attention layers)
    x = nd.array(qkv)
    x.attach_grad()
    import mxnet_tpu.autograd as ag
    with ag.record():
        s2 = mx.nd.contrib.interleaved_matmul_selfatt_qk(x, heads=H)
        l = s2.sum()
    l.backward()
    assert np.abs(x.grad.asnumpy()).sum() > 0


def test_correlation_op_kernel3():
    """kernel_size=3: window CENTERS at border=md+kr (reference
    correlation-inl.h indexing), checked against a direct numpy loop."""
    rng = np.random.RandomState(4)
    n, c, h, w = 1, 2, 10, 10
    d1 = rng.randn(n, c, h, w).astype(np.float32)
    d2 = rng.randn(n, c, h, w).astype(np.float32)
    md, k, kr = 1, 3, 1
    pad = md + kr
    out = nd.invoke("Correlation", nd.array(d1), nd.array(d2),
                    kernel_size=k, max_displacement=md, stride1=1,
                    stride2=1, pad_size=pad).asnumpy()
    D = 2 * md + 1
    border = md + kr
    ph = h + 2 * pad
    out_hw = ph - 2 * border
    assert out.shape == (n, D * D, out_hw, out_hw)
    p1 = np.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = np.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    sub = k * k * c
    for (dy, dx, oy, ox) in [(0, 0, 0, 0), (1, -1, 3, 2), (-1, 1, 5, 5)]:
        ch = (dy + md) * D + (dx + md)
        cy, cx = border + oy, border + ox        # window center, data1
        a = p1[0, :, cy - kr:cy + kr + 1, cx - kr:cx + kr + 1]
        b = p2[0, :, cy + dy - kr:cy + dy + kr + 1,
               cx + dx - kr:cx + dx + kr + 1]
        expect = (a * b).sum() / sub
        np.testing.assert_allclose(out[0, ch, oy, ox], expect, rtol=1e-4)
