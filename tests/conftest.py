"""Test harness configuration.

Forces the CPU platform with 8 virtual devices BEFORE jax initializes, so the
whole suite exercises multi-device mesh code paths without TPU hardware
(SURVEY.md §4: the reference re-runs its CPU suite on gpu(0); we are
context-parametric the same way via MXNET_TEST_DEVICE).
"""
import os

_accel_run = (os.environ.get("MXNET_TEST_DEVICE", "cpu").split("(")[0]
              in ("tpu", "gpu"))
if not _accel_run:
    os.environ["JAX_PLATFORMS"] = "cpu"
else:
    # On-chip suite run (MXNET_TEST_DEVICE=tpu): keep the real accelerator
    # backend registered — the host cpu backend coexists for the
    # cpu-vs-accel consistency sweep — and let the mesh helpers fall back
    # to the 8 virtual host devices for multi-device tests the single
    # chip can't satisfy (reference: gpu suite re-runs on gpu(0) while
    # multi-GPU tests stay on their own rigs, SURVEY §4).
    os.environ.setdefault("MXNET_MESH_HOST_FALLBACK", "1")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    flags = flags + " --xla_force_host_platform_device_count=8"

_COLLECTIVE_FLAGS = (
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
    " --xla_cpu_collective_call_terminate_timeout_seconds=600")


def _collective_flags_supported(base_flags):
    """XLA aborts the whole process on unknown XLA_FLAGS, and the cpu
    collective-watchdog flags only exist in newer jaxlibs — probe in a
    subprocess so an older jaxlib runs the suite without them instead of
    CHECK-aborting every test (observed with jaxlib 0.4.36)."""
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(base_flags + _COLLECTIVE_FLAGS).strip())
    try:
        return subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=180).returncode == 0
    except Exception:
        return False


if "collective_call_terminate_timeout" not in flags and \
        _collective_flags_supported(flags):
    # one host core runs all 8 virtual devices serially: XLA:CPU's default
    # 40 s collective-rendezvous watchdog CHECK-aborts whole test runs
    # whenever per-shard compute skews arrivals (seen on the big-shape
    # mesh tests under suite load)
    flags = flags + _COLLECTIVE_FLAGS
os.environ["XLA_FLAGS"] = flags.strip()

# The axon sitecustomize re-registers its TPU backend and resets
# jax_platforms AFTER env vars are read, so the env var alone is not enough —
# force the config back to cpu before any backend initializes.
import jax

if not _accel_run:
    jax.config.update("jax_platforms", "cpu")
else:
    # Fail FAST and LOUD if the accelerator silently fell back to the
    # host: a green "on-chip" suite on 8 virtual CPUs would be fake
    # evidence. chip_capture.write_suite_artifact greps this line.
    _backend = jax.default_backend()
    print("on-chip suite backend:", _backend, flush=True)
    assert _backend != "cpu", (
        "MXNET_TEST_DEVICE=%s but jax initialized the cpu backend — "
        "refusing to record a host run as on-chip evidence"
        % os.environ["MXNET_TEST_DEVICE"])

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seed_rng(request):
    """reference: tests/python/unittest/common.py (@with_seed) — seed and log
    the RNG per test for reproducibility."""
    seed = np.random.randint(0, 2 ** 31)
    env = os.environ.get("MXNET_TEST_SEED")
    if env:
        seed = int(env)
    import mxnet_tpu as mx
    mx.random.seed(seed)
    np.random.seed(seed)
    request.node.user_properties.append(("mxnet_test_seed", seed))
    yield


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running (full-size model zoo / multi-process)")
    config.addinivalue_line("markers", "lint: tracelint self-check (mx.analysis over mxnet_tpu/; run alone with -m lint)")
    config.addinivalue_line("markers", "obs: observability endpoint tests (live /metrics HTTP server on localhost)")
    config.addinivalue_line("markers", "serve: serving-engine tests (continuous batching, paged KV cache, replica supervision)")
    config.addinivalue_line("markers", "pallas: Pallas kernel parity tests (CPU backend runs the real kernels through the interpreter — parity evidence only, never perf evidence)")
    config.addinivalue_line("markers", "compiler: whole-graph symbolic compiler + AOT executable cache tests (run alone with -m compiler)")
    config.addinivalue_line("markers", "chaos: seeded multi-fault soak over the resilience fault sites (tools/chaos.py; run with -m chaos)")


@pytest.fixture(autouse=True)
def _pallas_interpret_mode(request, monkeypatch):
    """Tests marked `pallas` run every kernel through the Pallas
    interpreter on the CPU backend (this container has no TPU chip); the
    on-chip suite (MXNET_TEST_DEVICE=tpu) clears any inherited interpret
    flag so the native Mosaic path cannot be silently skipped."""
    if request.node.get_closest_marker("pallas") is not None:
        from mxnet_tpu.test_utils import is_accel_test_device
        if is_accel_test_device():
            monkeypatch.delenv("MXNET_FLASH_INTERPRET", raising=False)
        else:
            monkeypatch.setenv("MXNET_FLASH_INTERPRET", "1")
    yield
