"""Model zoo construction + forward smoke. reference idiom:
tests/python/unittest/test_gluon_model_zoo.py (build each model, run a
small forward, check output shape)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo import get_model

# (name, input hw) — inception wants 299; squeezenet's fixed 13x13 avgpool
# and densenet/vgg 7x7 pools want 224.
FAST_MODELS = [
    ("resnet18_v1", 224), ("resnet18_v2", 224),
    ("mobilenet0.25", 224), ("mobilenetv2_0.25", 224),
    ("squeezenet1.1", 224),
]
SLOW_MODELS = [
    ("resnet50_v1", 224), ("vgg11", 224), ("vgg11_bn", 224),
    ("alexnet", 224), ("densenet121", 224), ("inceptionv3", 299),
    ("squeezenet1.0", 224), ("mobilenet1.0", 224),
    ("mobilenetv2_1.0", 224),
]


@pytest.mark.parametrize("name,hw", FAST_MODELS)
def test_model_forward(name, hw):
    net = get_model(name, classes=10)
    net.initialize()
    x = nd.random_uniform(shape=(1, 3, hw, hw))
    out = net(x)
    assert out.shape == (1, 10)
    assert np.isfinite(out.asnumpy()).all()


@pytest.mark.parametrize("name,hw", SLOW_MODELS)
@pytest.mark.slow
def test_model_forward_slow(name, hw):
    net = get_model(name, classes=10)
    net.initialize()
    x = nd.random_uniform(shape=(1, 3, hw, hw))
    out = net(x)
    assert out.shape == (1, 10)


def test_get_model_unknown_raises():
    with pytest.raises(ValueError):
        get_model("resnet999_v9")


def test_hybridize_and_export(tmp_path):
    net = get_model("resnet18_v1", classes=10)
    net.initialize()
    net.hybridize()
    x = nd.random_uniform(shape=(1, 3, 224, 224))
    out1 = net(x)
    out2 = net(x)  # cached path
    np.testing.assert_allclose(out1.asnumpy(), out2.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_bert_model_zoo():
    """gluon.model_zoo.bert (reference: GluonNLP BERTModel on the
    _contrib_interleaved_matmul_selfatt_* op surface): forward shapes,
    valid_length masking isolates padding, backward, hybridize."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon
    from mxnet_tpu.gluon.model_zoo import bert

    m = bert.BERTModel(vocab_size=100, units=32, hidden_size=64,
                       num_layers=2, num_heads=4, max_length=64,
                       dropout=0.1)
    m.initialize(mx.init.Xavier(), ctx=mx.cpu())
    rng = np.random.RandomState(0)
    tok = nd.array(rng.randint(0, 100, (3, 10)), dtype="float32")
    tt = nd.zeros((3, 10))
    vl = nd.array([10, 7, 4], dtype="float32")
    seq, pooled, nsp, mlm = m(tok, tt, vl)
    assert seq.shape == (3, 10, 32)
    assert pooled.shape == (3, 32)
    assert nsp.shape == (3, 2)
    assert mlm.shape == (3, 10, 100)

    # perturbing PADDED tokens must not change valid positions
    tok2 = tok.asnumpy().copy()
    tok2[1, 7:] = 55
    seq2 = m(nd.array(tok2), tt, vl)[0]
    np.testing.assert_allclose(seq.asnumpy()[1, :7], seq2.asnumpy()[1, :7],
                               atol=1e-5)

    lossf = gluon.loss.SoftmaxCrossEntropyLoss()
    lbl = nd.array(rng.randint(0, 2, (3,)), dtype="float32")
    with autograd.record():
        logits = m(tok, tt, vl)[2]
        L = lossf(logits, lbl).mean()
    L.backward()
    assert np.isfinite(float(L.asnumpy()))

    m.hybridize()
    s2 = m(tok, tt, vl)[0]
    assert s2.shape == (3, 10, 32)

    # presets resolve
    big = bert.get_bert_model("bert_12_768_12")
    assert big.encoder._num_heads == 12


def test_resnet_nhwc_layout_matches_nchw():
    """Zoo resnet layout='NHWC' (channels-last, the TPU-native layout)
    computes the same function as NCHW given transposed weights."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import vision

    mx.random.seed(5)
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 32, 32).astype(np.float32)

    n1 = vision.resnet18_v1(classes=10)
    n1.initialize(mx.init.Xavier(), ctx=mx.cpu())
    y1 = n1(nd.array(x)).asnumpy()

    n2 = vision.resnet18_v1(classes=10, layout="NHWC")
    n2.initialize(mx.init.Xavier(), ctx=mx.cpu())
    xl = nd.array(np.transpose(x, (0, 2, 3, 1)))
    n2(xl)
    for (k1, a), (k2, b) in zip(sorted(n1.collect_params().items()),
                                sorted(n2.collect_params().items())):
        arr = a.data().asnumpy()
        if arr.ndim == 4 and b.shape != arr.shape:
            arr = np.transpose(arr, (0, 2, 3, 1))   # OIHW -> OHWI
        b.set_data(nd.array(arr))
    y2 = n2(xl).asnumpy()
    np.testing.assert_allclose(y1, y2, rtol=1e-4, atol=1e-4)


def test_bert_save_load_roundtrip():
    """Zoo BERT parameters roundtrip through the .params format."""
    import os
    import tempfile
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.model_zoo import bert

    kw = dict(vocab_size=50, units=16, hidden_size=32, num_layers=1,
              num_heads=2, max_length=16, dropout=0.0)
    m = bert.BERTModel(**kw)
    m.initialize(mx.init.Normal(0.02), ctx=mx.cpu())
    tok = nd.array(np.random.RandomState(0).randint(0, 50, (2, 8)),
                   dtype="float32")
    y1 = m(tok)[0].asnumpy()
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "bert.params")
        m.save_parameters(p)
        m2 = bert.BERTModel(**kw)
        m2.load_parameters(p, ctx=mx.cpu())
        y2 = m2(tok)[0].asnumpy()
    np.testing.assert_allclose(y1, y2, rtol=1e-6)
