"""Headline benchmark: ResNet-50 training throughput (images/sec/chip)
through the USER-FACING Gluon API — `gluon.model_zoo.vision.resnet50_v1` +
`gluon.Trainer` + `gluon.FusedTrainStep` (the whole train step compiled to
one XLA program; reference analog: CachedOp + engine-overlapped KVStore +
optimizer ops, SURVEY.md §3.2).

BASELINE.md: target >= 0.9x A100 per-chip throughput. A100 ResNet-50 train
(fp16/AMP, batch 256) is ~2500 img/s, so vs_baseline is measured against
0.9 * 2500 = 2250 img/s. Synthetic data, bf16 conv stack with fp32
BatchNorm, SGD+momentum, warm-up then steady-state mean over 50 steps.

BENCH=functional selects the raw functional-JAX path (models/resnet.py) for
comparison; the headline is the Gluon path.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}
"""
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as _np

BASELINE_IMG_S = 2250.0

# set by _probe_backend when the accelerator is unreachable; folded into the
# telemetry counters at emit time (importing mxnet_tpu inside the probe would
# initialize the very backend the probe guards against)
_FELL_BACK = False


def _emit(payload):
    """Print the single bench JSON line, with the telemetry counters that
    explain WHY a number moved: total jit compiles and whether the run
    silently fell back to cpu (the BENCH_r05 failure mode). Every row is
    stamped with its environment fingerprint and appended to the rolling
    bench history (tools/benchdb.py) so tools/check_bench.py can gate on
    regressions without ever comparing rows from different stacks."""
    try:
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools"))
        import benchdb
        fp = benchdb.fingerprint(
            backend=jax.default_backend(),
            device_count=jax.device_count(),
            cpu_fallback=_FELL_BACK)
        payload["backend"] = fp["backend"]
        payload["fingerprint"] = fp
        payload["fingerprint_id"] = benchdb.fingerprint_id(fp)
        payload["ts"] = round(time.time(), 3)
    except Exception as e:   # fingerprinting must never break the row
        print("# bench fingerprint unavailable: %s" % e, file=sys.stderr)
        benchdb = None
    try:
        from mxnet_tpu import telemetry
        if _FELL_BACK:
            telemetry.inc("device.fallback_cpu")
        snap = telemetry.snapshot()["counters"] if telemetry.ENABLED else {}
        payload["counters"] = {
            "compile": (snap.get("cachedop.compile", 0)
                        + snap.get("fused_step.compile", 0)
                        + snap.get("train_step.compile", 0)),
            "cachedop_retrace": snap.get("cachedop.retrace", 0),
            "device_fallback": snap.get("device.fallback_cpu",
                                        1 if _FELL_BACK else 0),
            "sync_asnumpy": snap.get("ndarray.sync.asnumpy", 0),
            # a noisy run (retried comm, watchdog stalls, restores) must be
            # distinguishable from a clean one in the bench history
            "resilience_faults": snap.get("resilience.faults_injected", 0),
            "resilience_retries": snap.get("resilience.retries", 0),
            "resilience_stalls": snap.get("resilience.stalls", 0),
            "resilience_restores": snap.get("resilience.restores", 0),
            "anomalies": snap.get("telemetry.anomaly.step_time", 0),
        }
        # rolling p50/p99 step latency (telemetry v2): the tail-latency
        # numbers the serving engine will be graded on, landed early. Pick
        # the step site that actually ran this bench.
        quants = telemetry.step_quantiles() or {}
        if quants:
            site = max(quants, key=lambda s: quants[s]["n"])
            payload.setdefault("step_ms_p50",
                               round(quants[site]["p50"], 3))
            payload.setdefault("step_ms_p99",
                               round(quants[site]["p99"], 3))
    except Exception as e:   # telemetry must never break the bench row
        print("# telemetry counters unavailable: %s" % e, file=sys.stderr)
    print(json.dumps(payload))
    if benchdb is not None and "fingerprint_id" in payload:
        benchdb.append(payload)


def _sync(x):
    """True device barrier. On the axon PjRt tunnel `block_until_ready`
    can return before execution finishes (verified 2026-07-30: a matmul
    loop \"completed\" in 0.3 ms, then asnumpy waited 0.5 s), so a real
    D2H transfer of one element is the only trustworthy sync point —
    exactly MXNet's `.asnumpy()` semantics (SURVEY §3.1)."""
    jax.block_until_ready(x)
    leaf = jax.tree_util.tree_leaves(x)[0]
    _np.asarray(jax.device_get(leaf.reshape(-1)[:1] if leaf.ndim else leaf))
LR = 0.1
MOMENTUM = 0.9


def bench_functional(on_accel):
    """Functional-JAX comparison path (round-1 headline)."""
    from mxnet_tpu.models.resnet import (CONFIGS, resnet_init, resnet_loss,
                                         update_running_stats)

    def tmap(f, *t):
        return jax.tree_util.tree_map(f, *t)

    cfg = CONFIGS["resnet50"] if on_accel else CONFIGS["resnet_tiny"]
    batch = 256 if on_accel else 8
    size = 224 if on_accel else 32
    steps, warmup = (50, 10) if on_accel else (5, 2)

    params = resnet_init(jax.random.PRNGKey(0), cfg)
    mom = tmap(jnp.zeros_like, params)
    images = jax.random.normal(jax.random.PRNGKey(1),
                               (batch, size, size, 3), jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0,
                                cfg.classes)
    data = {"images": images, "labels": labels}

    @jax.jit
    def step(params, mom, data):
        (loss, stats), grads = jax.value_and_grad(
            resnet_loss, has_aux=True)(params, data, cfg)
        mom = tmap(lambda m, g: MOMENTUM * m + g.astype(m.dtype), mom, grads)
        params = tmap(lambda p, m: (p - LR * m.astype(p.dtype)).astype(p.dtype),
                      params, mom)
        params = update_running_stats(params, stats, cfg)
        return params, mom, loss

    for _ in range(warmup):
        params, mom, loss = step(params, mom, data)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, mom, loss = step(params, mom, data)
    _sync(loss)
    dt = time.perf_counter() - t0
    return batch * steps / dt, "functional"


def bench_gluon(on_accel, layout="NCHW"):
    """The user-facing path: zoo model + Trainer + FusedTrainStep.
    layout='NHWC' runs the zoo model channels-last (the TPU-native
    layout the functional path uses)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon.model_zoo import vision

    ctx = mx.tpu() if on_accel else mx.cpu()
    batch = 256 if on_accel else 8
    size = 224 if on_accel else 32
    steps, warmup = (50, 10) if on_accel else (5, 2)

    mx.random.seed(0)
    with mx.Context(ctx):
        net = (vision.resnet50_v1(classes=1000, layout=layout) if on_accel
               else vision.resnet18_v1(classes=10, layout=layout))
        net.initialize(mx.init.Xavier(rnd_type="gaussian"), ctx=ctx)
        net.cast("bfloat16")  # conv stack bf16; BatchNorm stays fp32
        net.hybridize(static_alloc=True)

        rng = np.random.RandomState(1)
        shape = ((batch, 3, size, size) if layout == "NCHW"
                 else (batch, size, size, 3))
        x = nd.array(rng.randn(*shape), ctx=ctx, dtype="bfloat16")
        y = nd.array(rng.randint(0, 10, (batch,)), ctx=ctx, dtype="float32")
        net(x)  # shape inference + param init

        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": LR, "momentum": MOMENTUM})
        fused = gluon.FusedTrainStep(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer)

        for _ in range(warmup):
            loss = fused(x, y)
        _sync(loss.data_jax)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = fused(x, y)
        _sync(loss.data_jax)
        dt = time.perf_counter() - t0
    return batch * steps / dt, "gluon"


def bench_bert(on_accel):
    """Config #3: BERT-base masked-LM training tok/s (BASELINE.json
    configs[2]). models/bert.py + fused jit step (forward+loss+backward+
    AdamW in one XLA program), bf16, flash attention. Protocol: seq 128
    (MLPerf phase-1 convention), warm-up then steady-state mean.

    vs_baseline: 0.9 x A100 BERT-base fp16 pretrain throughput
    (~1,100 seq/s @ seq 128 = 140.8k tok/s) -> bar 126,720 tok/s."""
    from mxnet_tpu.models.bert import CONFIGS, bert_init, bert_mlm_loss

    def tmap(f, *t):
        return jax.tree_util.tree_map(f, *t)

    cfg = CONFIGS["bert_base"] if on_accel else CONFIGS["bert_tiny"]
    batch, seq = (128, 128) if on_accel else (4, 32)
    steps, warmup = (50, 10) if on_accel else (4, 2)
    lr, b1, b2, eps, wd = 1e-4, 0.9, 0.999, 1e-6, 0.01

    params = bert_init(jax.random.PRNGKey(0), cfg)
    m = tmap(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    v = tmap(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    tokens = jax.random.randint(k1, (batch, seq), 0, cfg.vocab_size)
    targets = jax.random.randint(k2, (batch, seq), 0, cfg.vocab_size)
    mask = (jax.random.uniform(k3, (batch, seq)) < 0.15).astype(jnp.int32)
    data = {"tokens": tokens, "targets": targets, "mask": mask}

    @jax.jit
    def step(params, m, v, t, data):
        loss, grads = jax.value_and_grad(bert_mlm_loss)(params, data, cfg)
        t = t + 1
        corr = jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)

        def upd(p, g, mi, vi):
            g32 = g.astype(jnp.float32)
            mi = b1 * mi + (1 - b1) * g32
            vi = b2 * vi + (1 - b2) * g32 * g32
            newp = p.astype(jnp.float32) - lr * (
                corr * mi / (jnp.sqrt(vi) + eps) + wd * p.astype(jnp.float32))
            return newp.astype(p.dtype), mi, vi

        flat_p, tree = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(m)
        flat_v = jax.tree_util.tree_leaves(v)
        new = [upd(p, g, mi, vi) for p, g, mi, vi in
               zip(flat_p, flat_g, flat_m, flat_v)]
        params = jax.tree_util.tree_unflatten(tree, [n[0] for n in new])
        m2 = jax.tree_util.tree_unflatten(tree, [n[1] for n in new])
        v2 = jax.tree_util.tree_unflatten(tree, [n[2] for n in new])
        return params, m2, v2, t, loss

    t = jnp.int32(0)
    for _ in range(warmup):
        params, m, v, t, loss = step(params, m, v, t, data)
    _sync(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, m, v, t, loss = step(params, m, v, t, data)
    _sync(loss)
    dt = time.perf_counter() - t0
    return batch * seq * steps / dt, "bert"


def bench_bert_gluon(on_accel):
    """Config #3 through the USER-FACING Gluon API: model_zoo BERT
    (fused interleaved-selfatt ops) + Trainer + FusedTrainStep — the BERT
    analog of the Gluon ResNet headline. Same protocol/bar as BENCH=bert."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon.model_zoo import bert as zoo_bert

    ctx = mx.tpu() if on_accel else mx.cpu()
    batch, seq = (128, 128) if on_accel else (4, 32)
    steps, warmup = (50, 10) if on_accel else (4, 2)
    vocab = 30522 if on_accel else 256

    mx.random.seed(0)
    with mx.Context(ctx):
        if on_accel:
            net = zoo_bert.bert_12_768_12(dropout=0.0)
        else:
            net = zoo_bert.BERTModel(vocab_size=vocab, units=64,
                                     hidden_size=128, num_layers=2,
                                     num_heads=4, max_length=seq,
                                     dropout=0.0)
        net.initialize(mx.init.Normal(0.02), ctx=ctx)
        net.cast("bfloat16")
        net.hybridize(static_alloc=True)

        rng = np.random.RandomState(1)
        x = nd.array(rng.randint(0, vocab, (batch, seq)), ctx=ctx,
                     dtype="float32")
        y = nd.array(rng.randint(0, vocab, (batch, seq)), ctx=ctx,
                     dtype="float32")
        net(x)

        sce = gluon.loss.SoftmaxCrossEntropyLoss()

        def mlm_loss(out, label):
            # out = (seq_out, pooled, nsp_logits, mlm_logits)
            return sce(out[3], label)

        trainer = gluon.Trainer(net.collect_params(), "adamw",
                                {"learning_rate": 1e-4, "wd": 0.01})
        fused = gluon.FusedTrainStep(net, mlm_loss, trainer)

        for _ in range(warmup):
            loss = fused(x, y)
        _sync(loss.data_jax)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = fused(x, y)
        _sync(loss.data_jax)
        dt = time.perf_counter() - t0
    return batch * seq * steps / dt, "bert_gluon"


def bench_fused_stage(on_accel):
    """ROOFLINE.md fusion project microbench: one ResNet stage-3-shaped
    conv3x3+BN+ReLU block, XLA composed vs Pallas fused
    (MXNET_TPU_USE_PALLAS). Reports the fused/composed speedup and logs
    both programs' HBM bytes from cost_analysis."""
    import numpy as onp
    from mxnet_tpu.ops import fused_conv as fc

    N, H, W, C = (64, 14, 14, 256) if on_accel else (4, 14, 14, 32)
    rng = onp.random.RandomState(0)
    dt = jnp.bfloat16 if on_accel else jnp.float32
    x = jnp.asarray(rng.randn(N, H, W, C), dtype=dt)
    w = jnp.asarray(rng.randn(3, 3, C, C) * 0.05, dtype=dt)
    scale = jnp.asarray(rng.rand(C) + 0.5, dtype=jnp.float32)
    shift = jnp.asarray(rng.randn(C) * 0.1, dtype=jnp.float32)

    res = jnp.asarray(rng.randn(N, H, W, C) * 0.1, dtype=dt)
    composed = jax.jit(
        lambda a: fc._xla_conv_bn_relu(a, w, scale, shift, residual=res))
    fused = jax.jit(
        lambda a: fc._pallas_conv_bn_relu(a, w, scale, shift, residual=res))

    for fn, tag in ((composed, "xla"), (fused, "pallas")):
        lowered = fn.lower(x)
        try:
            cost = lowered.compile().cost_analysis()
            cost = cost[0] if isinstance(cost, list) else cost
            print("# %s bytes accessed: %.3e" % (
                tag, cost.get("bytes accessed", float("nan"))),
                file=sys.stderr)
        except Exception as e:       # cost analysis is best-effort
            print("# %s cost_analysis unavailable: %s" % (tag, e),
                  file=sys.stderr)

    def time_it(fn):
        fn(x).block_until_ready()
        n = 50 if on_accel else 5
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(x)
        out.block_until_ready()
        return n * N / (time.perf_counter() - t0)

    base = time_it(composed)
    fast = time_it(fused)
    return fast, base


def bench_fused_train_stage(on_accel):
    """Round-5 training-fusion microbench: one ResNet stage-3-shaped
    conv3x3+BN(batch stats)+ReLU TRAINING step (fwd+bwd), XLA composed vs
    the fused op (`_contrib_conv_bn_relu_train`: stats in the conv
    epilogue, xhat recomputed in backward). Logs both programs'
    cost_analysis bytes to stderr; value = fused img/s, vs_baseline =
    fused/composed speedup."""
    import numpy as onp
    from mxnet_tpu.ops import fused_conv as fc

    N, H, W, C = (64, 14, 14, 256) if on_accel else (4, 8, 8, 16)
    rng = onp.random.RandomState(0)
    dt = jnp.bfloat16 if on_accel else jnp.float32
    x = jnp.asarray(rng.randn(N, H, W, C), dtype=dt)
    w = jnp.asarray(rng.randn(3, 3, C, C) * 0.05, dtype=dt)
    gamma = jnp.asarray(rng.rand(C) + 0.5, dtype=jnp.float32)
    beta = jnp.asarray(rng.randn(C) * 0.1, dtype=jnp.float32)
    cot = jnp.asarray(rng.rand(N, H, W, C), dtype=dt)

    def composed(x_, w_, g_, b_):
        from jax import lax
        conv = lax.conv_general_dilated(
            x_, w_, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)
        mean = jnp.mean(conv, axis=(0, 1, 2))
        var = jnp.var(conv, axis=(0, 1, 2))
        y = (conv - mean) * jax.lax.rsqrt(var + 1e-3) * g_ + b_
        return jnp.maximum(y, 0.0).astype(x_.dtype)

    def fused(x_, w_, g_, b_):
        out, _, _ = fc._cbr_train(1e-3, False, x_, w_, g_, b_, None)
        return out

    def train_step(fn):
        def step(x_, w_, g_, b_):
            loss_fn = lambda *a: jnp.sum(fn(*a).astype(jnp.float32)
                                         * cot.astype(jnp.float32))
            return jax.grad(loss_fn, argnums=(1, 2, 3))(x_, w_, g_, b_)
        return jax.jit(step)

    results = {}
    for fn, tag in ((train_step(composed), "xla_composed"),
                    (train_step(fused), "pallas_fused")):
        lowered = fn.lower(x, w, gamma, beta)
        try:
            cost = lowered.compile().cost_analysis()
            cost = cost[0] if isinstance(cost, list) else cost
            print("# fused_train %s bytes accessed: %.3e" % (
                tag, cost.get("bytes accessed", float("nan"))),
                file=sys.stderr)
        except Exception as e:
            print("# fused_train %s cost_analysis unavailable: %s"
                  % (tag, e), file=sys.stderr)
        out = fn(x, w, gamma, beta)
        _sync(out[0])
        n = 50 if on_accel else 5
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(x, w, gamma, beta)
        _sync(out[0])
        results[tag] = n * N / (time.perf_counter() - t0)
    return results["pallas_fused"], results["xla_composed"]


def bench_fused_bwd(on_accel):
    """BENCH=fused_bwd (ISSUE 10): the fused CBR BACKWARD program vs the
    composed Conv->BN(batch stats)->ReLU backward, isolated via jax.vjp —
    the lowered program is the pure backward, whose inputs are whatever
    each forward SAVED. The composed path materializes/loads its AD
    residuals (xhat, pre-relu activation); the fused custom-vjp re-streams
    conv_out through `_kernel_train_bwd` twice and loads nothing else.
    Logs both programs' cost_analysis bytes (round-3 CPU-backend
    methodology off-chip; interpret-mode wall times are NOT perf
    evidence) and emits bytes_fused/bytes_composed in the row."""
    import numpy as onp
    from jax import lax
    from mxnet_tpu.ops import fused_conv as fc

    N, H, W, C = (64, 14, 14, 256) if on_accel else (4, 8, 8, 16)
    rng = onp.random.RandomState(0)
    dt = jnp.bfloat16 if on_accel else jnp.float32
    x = jnp.asarray(rng.randn(N, H, W, C), dtype=dt)
    w = jnp.asarray(rng.randn(3, 3, C, C) * 0.05, dtype=dt)
    gamma = jnp.asarray(rng.rand(C) + 0.5, dtype=jnp.float32)
    beta = jnp.asarray(rng.randn(C) * 0.1, dtype=jnp.float32)
    cot = (jnp.asarray(rng.rand(N, H, W, C), dtype=dt),
           jnp.zeros((C,), jnp.float32), jnp.zeros((C,), jnp.float32))

    def composed(x_, w_, g_, b_):
        conv = lax.conv_general_dilated(
            x_, w_, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)
        mean = jnp.mean(conv, axis=(0, 1, 2))
        var = jnp.var(conv, axis=(0, 1, 2))
        xhat = (conv - mean) * jax.lax.rsqrt(var + 1e-3)
        out = jnp.maximum(xhat * g_ + b_, 0.0).astype(x_.dtype)
        return out, mean, var

    def fused(x_, w_, g_, b_):
        return fc._cbr_train(1e-3, False, x_, w_, g_, b_, None)

    speed, bytes_ = {}, {}
    for f, tag in ((composed, "composed"), (fused, "fused")):
        _, vjp = jax.vjp(f, x, w, gamma, beta)
        bwd = jax.jit(lambda c, vjp=vjp: vjp(c))
        try:
            cost = bwd.lower(cot).compile().cost_analysis()
            cost = cost[0] if isinstance(cost, list) else cost
            bytes_[tag] = cost.get("bytes accessed", float("nan"))
            print("# fused_bwd %s bytes accessed: %.3e"
                  % (tag, bytes_[tag]), file=sys.stderr)
        except Exception as e:          # cost analysis is best-effort
            bytes_[tag] = None
            print("# fused_bwd %s cost_analysis unavailable: %s"
                  % (tag, e), file=sys.stderr)
        out = bwd(cot)
        _sync(out[0])
        n = 50 if on_accel else 5
        t0 = time.perf_counter()
        for _ in range(n):
            out = bwd(cot)
        _sync(out[0])
        speed[tag] = n * N / (time.perf_counter() - t0)
    return {
        "metric": ("fused_cbr_bwd_img_per_sec" if on_accel
                   else "fused_cbr_bwd_cpu_img_per_sec"),
        "value": round(speed["fused"], 2),
        "unit": "img/s",
        "vs_baseline": round(speed["fused"] / speed["composed"], 4),
        "bytes_fused": bytes_["fused"],
        "bytes_composed": bytes_["composed"],
    }


def bench_fused_opt(on_accel):
    """BENCH=fused_opt (ISSUE 10): the Pallas flat-segment Adam kernel vs
    the XLA composite `_fused_flat_xla` over a resnet18-sized flat shard
    (one pass over w/g/mean/var instead of separate elementwise loops).
    Emits elems/s, vs_baseline = pallas/xla wall ratio, and both
    programs' cost_analysis bytes. Off-chip the kernel runs through the
    interpreter, whose per-grid-step block-copy emulation (dynamic-slice/
    update-slice pairs) DOMINATES the counted bytes for a pure
    elementwise kernel — the cpu row is a dispatch-correctness smoke
    (expect bytes_fused > bytes_composed and vs_baseline < 1 there); the
    chip-queue row is the evidence, as with BENCH=comm."""
    import numpy as onp
    from mxnet_tpu.ops import fused_optimizer as fo
    from mxnet_tpu.optimizer.optimizer import _fused_flat_xla

    n = 11_700_000 if on_accel else 262_144
    rng = onp.random.RandomState(0)
    w = jnp.asarray(rng.randn(n).astype(onp.float32))
    g = jnp.asarray(rng.randn(n).astype(onp.float32))
    mean = jnp.zeros((n,), jnp.float32)
    var = jnp.abs(g) * 0.1
    lr = jnp.full((n,), 0.001, jnp.float32)
    wd = jnp.full((n,), 0.01, jnp.float32)
    args = (w, g, mean, var, None, lr, wd, jnp.float32(0.9),
            jnp.float32(0.1), jnp.float32(0.999), jnp.float32(0.001),
            jnp.float32(1e-8), jnp.float32(1.0), jnp.float32(0.0))

    impls = {
        "composed": _fused_flat_xla("adam", True, False, False),
        "fused": fo.flat_update_fn("adam", True, False, False),
    }
    speed, bytes_ = {}, {}
    for tag, fn in impls.items():
        try:
            cost = jax.jit(fn).lower(*args).compile().cost_analysis()
            cost = cost[0] if isinstance(cost, list) else cost
            bytes_[tag] = cost.get("bytes accessed", float("nan"))
            print("# fused_opt %s bytes accessed: %.3e"
                  % (tag, bytes_[tag]), file=sys.stderr)
        except Exception as e:
            bytes_[tag] = None
            print("# fused_opt %s cost_analysis unavailable: %s"
                  % (tag, e), file=sys.stderr)
        out = fn(*args)
        _sync(out[0])
        reps = 50 if on_accel else 3
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        _sync(out[0])
        speed[tag] = reps * n / (time.perf_counter() - t0)
    return {
        "metric": ("fused_opt_flat_elems_per_sec" if on_accel
                   else "fused_opt_flat_cpu_elems_per_sec"),
        "value": round(speed["fused"], 2),
        "unit": "elems/s",
        "vs_baseline": round(speed["fused"] / speed["composed"], 4),
        "bytes_fused": bytes_["fused"],
        "bytes_composed": bytes_["composed"],
    }


def resnet18_grad_shapes():
    """resnet18 (classes=1000) parameter shapes: conv1 + 8 basic blocks
    (2 convs + 2 BN pairs each, stage-transition downsamples) + fc — the
    62-tensor gradient set the comm bench AND the acceptance test
    (tests/test_comm_bucket.py) sync."""
    shapes = [(64, 3, 7, 7), (64,), (64,)]
    widths = [(64, 64), (64, 128), (128, 256), (256, 512)]
    for cin, cout in widths:
        for blk in range(2):
            first_in = cin if blk == 0 else cout
            shapes += [(cout, first_in, 3, 3), (cout,), (cout,),
                       (cout, cout, 3, 3), (cout,), (cout,)]
            if blk == 0 and cin != cout:
                shapes += [(cout, cin, 1, 1), (cout,), (cout,)]
    shapes += [(1000, 512), (1000,)]
    return shapes


def bench_comm(on_accel):
    """BENCH=comm: gradient-sync microbench for the bucketed comm engine
    (mx.engine). A resnet18-shaped gradient set (62 tensors, ~11.7M params)
    rides one multi-key kvstore pushpull per step — first bucketed
    (MXNET_TPU_COMM_BUCKET_MB or the 25 MB default), then the per-param
    escape hatch (bucket=0) for the vs_baseline ratio. The JSON row carries
    `collectives_per_step` and `comm_bucket_bytes` from telemetry — the
    numbers that prove buckets, not per-param calls, hit the wire.

    Reading the row: on an accelerator the win is per-launch latency (62
    dispatches -> ~2), so vs_baseline > 1 is expected; the cpu smoke row
    has near-zero launch cost and pays the pack/unpack memcpy instead, so
    its vs_baseline < 1 — there the row is about `collectives_per_step`
    dropping below `params_per_step`, not the time ratio."""
    import mxnet_tpu as mx
    from mxnet_tpu import engine, nd, telemetry

    shapes = resnet18_grad_shapes()
    steps = 20 if on_accel else 5
    rng = _np.random.RandomState(0)
    # two replicas per key: both paths then do a REAL per-key reduce (the
    # 2-device aggregation shape), not a free store replace
    grads = [[nd.array(rng.randn(*s).astype(_np.float32)) for _ in range(2)]
             for s in shapes]
    outs = [[nd.zeros(s) for _ in range(2)] for s in shapes]
    nbytes = sum(g[0].size * 4 for g in grads)

    def run(bucket_mb):
        with engine.bucket_mb_scope(bucket_mb):
            kv = mx.kv.create("device")
            keys = list(range(len(shapes)))
            for k, s in zip(keys, shapes):
                kv.init(k, nd.zeros(s))
            kv.pushpull(keys, grads, out=outs)  # warm the fused programs
            _sync(outs[0][0].data_jax)
            telemetry.reset()
            t0 = time.perf_counter()
            for _ in range(steps):
                # one cat-`step` span per sync: the window the overlap
                # profiler (telemetry.attribution) decomposes
                ts = telemetry.span_clock()
                s0 = time.perf_counter()
                kv.pushpull(keys, grads, out=outs)
                telemetry.record_span("comm.step", "step", ts,
                                      time.perf_counter() - s0)
            _sync(outs[0][0].data_jax)
            dt = (time.perf_counter() - t0) / steps
            snap = telemetry.snapshot()["counters"]
            ovl = telemetry.overlap_report(site="comm.step")["summary"]
            return dt, snap, ovl

    dt_bucket, snap, ovl = run(None)  # env/default cap
    dt_flat, _, ovl_flat = run(0)     # per-param escape hatch
    # the decomposition is a partition: it must sum to step time (the
    # acceptance's 5% bound holds by construction; report the residue)
    parts = (ovl["compute_ms"] + ovl["collective_ms"] + ovl["host_ms"]
             + ovl["idle_ms"])
    payload = {
        "metric": ("comm_grad_sync_mb_per_sec" if on_accel
                   else "comm_grad_sync_cpu_mb_per_sec"),
        "value": round(nbytes / 1e6 / dt_bucket, 2),
        "unit": "MB/s",
        "vs_baseline": round(dt_flat / dt_bucket, 4),  # speedup vs per-param
        "params_per_step": len(shapes),
        "collectives_per_step": snap.get("comm.collectives", 0) // steps,
        "comm_bucket_bytes": snap.get("comm.bucket.bytes", 0) // steps,
        "comm_bucket_count": snap.get("comm.bucket.count", 0) // steps,
        # measured comm-overlap attribution (ROADMAP #4's autotuner input):
        # bucketed vs per-param overlap fraction + exposed collective ms
        "overlap_frac": ovl["overlap_frac"],
        "overlap_frac_flat": ovl_flat["overlap_frac"],
        "collective_ms_per_step": round(ovl["collective_ms"] / steps, 3),
        "collective_ms_per_step_flat":
            round(ovl_flat["collective_ms"] / steps, 3),
        "decomp_residue_pct": round(
            100.0 * abs(ovl["step_ms"] - parts) / max(ovl["step_ms"],
                                                      1e-9), 4),
    }
    return payload


def bench_comm_readiness(on_accel):
    """BENCH=comm extra legs (ISSUE 19): the readiness-ordered flush
    engine and the schedule autotuner, A/B'd against the
    reverse-registration engine on IDENTICAL traffic (same net, same
    seed, same batches — only the flush policy differs). Emitted as
    separate gated rows so check_bench tracks `overlap_frac_*` (up) and
    `collective_ms_*` (down) as first-class series.

    Reading the rows: `first_flush_before_backward_end=1` is the
    readiness engine's proof-of-life — the first bucket's collective
    launched while backward was still running, which the registration
    engine cannot do by construction (it first sees gradients at step
    time). `parity_ok` asserts the legs' final parameters stayed
    bit-identical, i.e. the overlap was free."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, engine, gluon, nd, telemetry
    from mxnet_tpu.gluon import nn

    steps = 12 if on_accel else 5
    widths = (512, 512, 256, 256, 128)
    cap_mb = 0.5   # several buckets per step: flushes can land mid-backward

    def run(comm_ready, env=None):
        prev_env = {}
        for k, v in (env or {}).items():
            prev_env[k] = os.environ.get(k)
            os.environ[k] = v
        try:
            with engine.bucket_mb_scope(None if env else cap_mb):
                mx.random.seed(0)
                rng = _np.random.RandomState(0)
                net = nn.HybridSequential()
                with net.name_scope():
                    for w in widths:
                        net.add(nn.Dense(w, activation="relu"))
                    net.add(nn.Dense(10))
                net.initialize(mx.init.Xavier())
                tr = gluon.Trainer(net.collect_params(), "sgd",
                                   {"learning_rate": 0.05},
                                   update_on_kvstore=True,
                                   comm_ready=comm_ready)
                x = nd.array(rng.randn(64, 256).astype(_np.float32))
                y = nd.array(rng.randn(64, 10).astype(_np.float32))
                loss_fn = gluon.loss.L2Loss()

                def one_step():
                    with autograd.record():
                        loss = loss_fn(net(x), y)
                    loss.backward()
                    tr.step(64)

                sweep = 0
                if env:   # autotuned leg: let the sweep finish first
                    while tr._autotune is None or not tr._autotune.done:
                        one_step()
                        sweep += 1
                        if sweep > 64:
                            break
                else:
                    for _ in range(2):
                        one_step()     # warm the fused programs
                telemetry.reset()
                for _ in range(steps):
                    one_step()
                _sync(net.collect_params().values().__iter__().__next__()
                      .data().data_jax)
                ovl = telemetry.overlap_report(
                    site="trainer.step")["summary"]
                snap = telemetry.snapshot()["counters"]
                params = [p.data().asnumpy()
                          for p in net.collect_params().values()]
                sched = engine.current_schedule()
                frac = ovl.get("overlap_frac")
                if frac is None and snap.get("comm.collectives", 0):
                    # no comm span inside the step window but collectives
                    # DID run: they all launched during backward — the
                    # whole comm phase is hidden, i.e. full overlap
                    frac = 1.0
                return {
                    "overlap_frac": frac,
                    "collective_ms": round(
                        ovl.get("collective_ms", 0.0) / steps, 3),
                    "first_flush_before_backward_end": min(1, snap.get(
                        "comm.ready.first_flush_before_backward_end", 0)),
                    "flush_during_backward": snap.get(
                        "comm.ready.flush_during_backward", 0) // steps,
                    "ready_rounds": snap.get("comm.ready.rounds", 0),
                    "sweep_steps": sweep,
                    "schedule": sched.describe() if sched else None,
                    "params": params,
                }
        finally:
            for k, v in prev_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            if env:
                engine.set_schedule(None)

    reg = run(False)
    rdy = run(True)
    tuned = run(None, env={"MXNET_TPU_COMM_AUTOTUNE": "1",
                           "MXNET_TPU_COMM_AUTOTUNE_STEPS": "1",
                           "MXNET_TPU_COMM_AUTOTUNE_CAPS": "0,0.5,25"})
    parity = all(_np.array_equal(a, b)
                 for a, b in zip(reg["params"], rdy["params"]))
    unit_f, unit_ms = "frac", "ms"
    rows = [
        {"metric": "overlap_frac_comm_ready", "value": rdy["overlap_frac"],
         "unit": unit_f, "overlap_frac_registration": reg["overlap_frac"],
         "first_flush_before_backward_end":
             rdy["first_flush_before_backward_end"],
         "flush_during_backward_per_step": rdy["flush_during_backward"],
         "ready_rounds": rdy["ready_rounds"], "parity_ok": parity},
        {"metric": "collective_ms_comm_ready", "value": rdy["collective_ms"],
         "unit": unit_ms,
         "collective_ms_registration": reg["collective_ms"]},
        {"metric": "overlap_frac_comm_autotuned",
         "value": tuned["overlap_frac"], "unit": unit_f,
         "schedule": tuned["schedule"],
         "sweep_steps": tuned["sweep_steps"],
         "collective_ms_autotuned": tuned["collective_ms"]},
    ]
    return rows


def bench_zero(on_accel):
    """BENCH=zero: ZeRO-1 weight-update sharding microbench. A
    resnet18-shaped parameter set (62 tensors, ~11.7M params) trains
    through the kvstore with the optimizer ON the store, first as the
    ZeRO sharded updater (reduce-scatter → one fused flat shard update per
    dtype-bucket → all-gather), then as the replicated per-parameter
    updater for the vs_baseline ratio. The JSON row carries the ledger
    that grades a ZeRO implementation: `opt_state_bytes_per_rank`
    (sharded-state footprint — divide `opt_state_bytes_replicated` by the
    world size and you should land here), `collectives_per_step`, and
    `fused_update_ms` (mean host wall time of the fused shard dispatch).

    Single-process rows run at world=1 (the comm legs are identity), so
    the number that moves OFF-chip is dispatch count: 62 per-param
    optimizer launches collapse into one fused launch per bucket."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd, telemetry

    shapes = resnet18_grad_shapes()
    steps = 20 if on_accel else 5
    rng = _np.random.RandomState(0)
    grads = [nd.array(rng.randn(*s).astype(_np.float32)) for s in shapes]
    nbytes = sum(g.size * 4 for g in grads)

    def run(zero):
        kv = mx.kv.create("device")
        kv.set_optimizer(mx.optimizer.create(
            "sgd", learning_rate=0.1, momentum=0.9, rescale_grad=1.0),
            zero=zero)
        keys = list(range(len(shapes)))
        for k, s in zip(keys, shapes):
            kv.init(k, nd.array(rng.randn(*s).astype(_np.float32)))
        kv.push(keys, grads)  # warm the fused programs + freeze the layout
        _sync(kv._store["0"].data_jax)
        telemetry.reset()
        t0 = time.perf_counter()
        for _ in range(steps):
            kv.push(keys, grads)
        _sync(kv._store["0"].data_jax)
        dt = (time.perf_counter() - t0) / steps
        snap = telemetry.snapshot()
        return dt, snap

    dt_zero, snap = run(True)
    dt_repl, _ = run(False)
    counters = snap["counters"]
    hist = snap["histograms"].get("opt.fused_update_ms", {})
    state_bytes = snap["gauges"].get(
        "opt.state_bytes_per_rank", {}).get("value", 0)
    world = 1  # single-process bench; dist rows come from tools/launch.py
    return {
        "metric": ("zero_update_mb_per_sec" if on_accel
                   else "zero_update_cpu_mb_per_sec"),
        "value": round(nbytes / 1e6 / dt_zero, 2),
        "unit": "MB/s",
        "vs_baseline": round(dt_repl / dt_zero, 4),  # speedup vs replicated
        "params_per_step": len(shapes),
        "world": world,
        "opt_state_bytes_per_rank": int(state_bytes),
        "opt_state_bytes_replicated": int(state_bytes) * world,
        "collectives_per_step": counters.get("comm.collectives", 0) // steps,
        "reduce_scatter_per_step":
            counters.get("comm.reduce_scatter", 0) // steps,
        "all_gather_per_step": counters.get("comm.all_gather", 0) // steps,
        "fused_updates_per_step": hist.get("count", 0) // steps,
        "fused_update_ms": round(hist.get("sum", 0.0)
                                 / max(1, hist.get("count", 0)), 4),
    }


def bench_resilience(on_accel):
    """BENCH=resilience: recovery-path microbench for the resilience v2
    stack. A small Gluon MLP trains under `ResilientRunner` while the
    deterministic fault harness injects (a) a proactive preemption NOTICE
    through the maintenance poller (`preempt.poll` site — coordinated
    off-cadence checkpoint, zero replay) and (b) a reactive mid-run
    preemption (`run.step` site — restore-and-replay from the last
    periodic snapshot). The JSON row carries the ledger that grades a
    recovery stack: `recovery_time_s` (wall time inside restores),
    `replayed_steps` (work redone — the cost proactive checkpoints
    eliminate), and `proactive_ckpt` (notices converted to checkpoints).
    value = recovery_time_s; vs_baseline = fraction of run wall time lost
    to recovery (lower is better for both)."""
    import tempfile

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, resilience as rz
    from mxnet_tpu.resilience import faults
    from mxnet_tpu.resilience.preempt import PreemptionListener

    steps = 12
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer)
    rng = np.random.RandomState(0)
    X = rng.rand(steps, 32, 8).astype(np.float32)
    Y = rng.randint(0, 4, (steps, 32)).astype(np.float32)

    def batch_fn(i):
        # modulo: a rollback skip advances the data index past the last
        # pre-generated batch
        return nd.array(X[i % steps]), nd.array(Y[i % steps])

    ckpt_dir = tempfile.mkdtemp(prefix="bench_resilience_")
    # notice on the 2nd poll (proactive path: zero replay), hard preemption
    # at step 8 (reactive path: off the ckpt_every=3 cadence, so the
    # restore rewinds to step 6 and replays 2 completed steps — the cost
    # the proactive checkpoint avoids)
    listener = PreemptionListener(poll_interval_s=0.05)
    t0 = time.perf_counter()
    with faults.inject("preempt.poll:preempt:2;run.step:preempt:9"):
        runner = rz.ResilientRunner.for_fused_step(
            fused, batch_fn, ckpt_dir=ckpt_dir, ckpt_every=3,
            max_restarts=4, commit=True, preempt_listener=listener)
        report = runner.run(steps)
    listener.stop()
    total_s = time.perf_counter() - t0

    # --- integrity plane (PR 20) -------------------------------------
    # (a) sentinel overhead A/B: identical clean runs with the fused
    # all-finite check off vs on — the check is ONE scalar reduction
    # riding the already-materialised flat buckets plus one host sync,
    # budget <=2%. Measured on a model whose step time is realistic
    # (~20ms): the sync is a fixed per-step cost, and quoting it against
    # a sub-ms toy step would overstate it ~20x;
    # (b) rollback exercise: a corrupt batch plus a corrupt newest
    # snapshot drive rollback-to-last-good and the checksum fallback.
    def _with_integrity(value, fn):
        old = os.environ.get("MXNET_TPU_INTEGRITY")
        os.environ["MXNET_TPU_INTEGRITY"] = value
        try:
            return fn()
        finally:
            if old is None:
                os.environ.pop("MXNET_TPU_INTEGRITY", None)
            else:
                os.environ["MXNET_TPU_INTEGRITY"] = old

    def _ab_fused():
        mx.random.seed(0)
        n2 = gluon.nn.HybridSequential()
        with n2.name_scope():
            n2.add(gluon.nn.Dense(512, activation="relu"),
                   gluon.nn.Dense(512, activation="relu"),
                   gluon.nn.Dense(16))
        n2.initialize(mx.init.Xavier())
        t2 = gluon.Trainer(n2.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        return gluon.FusedTrainStep(
            n2, gluon.loss.SoftmaxCrossEntropyLoss(), t2)

    ab_rng = np.random.RandomState(1)
    ab_x = nd.array(ab_rng.rand(128, 256).astype(np.float32))
    ab_y = nd.array(ab_rng.randint(0, 16, (128,)).astype(np.float32))
    fused_off = _with_integrity("0", _ab_fused)  # sentinel baked at build
    fused_on = _with_integrity("1", _ab_fused)
    fused_off(ab_x, ab_y).asnumpy()  # compile outside the timed window
    _with_integrity("1", lambda: fused_on(ab_x, ab_y).asnumpy())

    def _chunk(fused2):
        n = 10
        t = time.perf_counter()
        for _ in range(n):
            # per-step loss sync in BOTH legs: the runner records a float
            # loss every step (run.py RunReport.losses) whether or not the
            # sentinel is on, so the A/B isolates the sentinel's marginal
            # cost — the fused reduction — not the loop's own sync
            fused2(ab_x, ab_y).asnumpy()
        return n / (time.perf_counter() - t)

    # paired chunks, median-of-8: adjacent off/on chunks share the box's
    # load conditions, so the per-pair ratio cancels drift and the median
    # sheds spike outliers
    pairs = []
    for _ in range(8):
        off = _chunk(fused_off)
        on = _with_integrity("1", lambda: _chunk(fused_on))
        pairs.append((off, on))
    ratios = sorted(on / off for off, on in pairs)
    mid = (ratios[3] + ratios[4]) / 2.0

    def _fresh_fused():
        mx.random.seed(0)
        n3 = gluon.nn.HybridSequential()
        with n3.name_scope():
            n3.add(gluon.nn.Dense(32, activation="relu"),
                   gluon.nn.Dense(4))
        n3.initialize(mx.init.Xavier())
        t3 = gluon.Trainer(n3.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        return gluon.FusedTrainStep(
            n3, gluon.loss.SoftmaxCrossEntropyLoss(), t3)

    def _rollback_leg():
        from mxnet_tpu import telemetry as _telem
        c0 = _telem.snapshot()["counters"].get(
            "checkpoint.corrupt_fallbacks", 0)
        fused3 = _fresh_fused()
        rb_dir = tempfile.mkdtemp(prefix="bench_rollback_")
        # the 3rd prepare is the step-4 snapshot — the NEWEST candidate
        # when the step-4 divergence rolls back, so the checksum fallback
        # path (restore 2, replay) actually runs
        with faults.inject("train.batch:corrupt:5;"
                           "checkpoint.corrupt:corrupt:3;"
                           "run.step:preempt:9"):
            rb_runner = rz.ResilientRunner.for_fused_step(
                fused3, batch_fn, ckpt_dir=rb_dir, ckpt_every=2,
                max_restarts=4)
            rb_report = rb_runner.run(steps)
        fallbacks = _telem.snapshot()["counters"].get(
            "checkpoint.corrupt_fallbacks", 0) - c0
        return rb_report, fallbacks

    rb_report, corrupt_restores = _with_integrity("1", _rollback_leg)

    return {
        "metric": ("resilience_recovery_time_s" if on_accel
                   else "resilience_cpu_recovery_time_s"),
        "value": round(report.recovery_time_s, 4),
        "unit": "s",
        "vs_baseline": round(report.recovery_time_s / total_s, 4),
        "recovery_time_s": round(report.recovery_time_s, 4),
        "replayed_steps": report.replayed_steps,
        "proactive_ckpt": report.proactive_ckpts,
        "restarts": report.restarts,
        "checkpoints": report.checkpoints,
        "rollbacks": rb_report.rollbacks,
        "skipped_batches": rb_report.skipped_batches,
        "corrupt_restores": corrupt_restores,
        "integrity_overhead_pct": round((1.0 - mid) * 100.0, 2),
    }


def bench_serve(on_accel):
    """BENCH=serve: continuous-batching inference bench for mx.serve
    under a BURST-arrival workload with a shared system prompt. Traffic
    arrives in waves (each wave a burst of requests, most sharing one
    system-prompt prefix), served twice over identical traffic:

    * **v2** — chunked multi-stream prefill + prefix sharing; on an
      accelerator speculative decoding joins this leg (decode is
      HBM-bound there — the regime spec exists for). On the CPU smoke
      row spec is measured in a SEPARATE short leg instead: the identity
      draft doubles compute per token, and on a compute-bound backend
      that rightly loses (the README's when-NOT table) — folding it in
      would let an anti-pattern config distort the SLO columns;
    * **v1-like baseline** — prefix sharing off, no draft, one
      max-context prefill row (the PR 12 batch-1-prefill behavior).

    The identity draft (bench models are random weights, so no *trained*
    small draft exists) exercises the full draft/verify machinery at its
    accept-rate upper bound; a distilled draft lands between accept=1
    and accept=0. vs_baseline = v2/v1 tokens_s; the row also carries the
    v1 numbers (baseline_tokens_s, baseline_ttft_ms_p99) so the TTFT win
    under bursts is visible, the serving SLO numbers (ttft/tpot
    p50/p99), and the attribution columns: accept_rate (spec drafts the
    target agreed with, from whichever leg ran spec), spec_tokens_s (the
    spec leg's own rate), prefix_hit_rate (admissions that reused cached
    prompt blocks), kv_blocks_saved (whole blocks of prefill+HBM skipped
    via sharing). Two deliberately oversized requests prove
    load-shedding sheds (structured Overloaded) instead of OOMing."""
    import dataclasses

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.models.llama import CONFIGS, llama_init

    if on_accel:
        cfg = CONFIGS["llama_110m"]
        n_req, base_new, blocks, bs, batch = 32, 32, 512, 16, 8
        sys_len, waves = 48, 4
    else:
        cfg = dataclasses.replace(CONFIGS["llama_tiny"],
                                  dtype=jnp.float32, max_seq_len=64)
        n_req, base_new, blocks, bs, batch = 12, 8, 96, 8, 8
        sys_len, waves = 24, 2
    params = llama_init(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    sys_prompt = rng.randint(1, cfg.vocab_size - 1, size=sys_len).tolist()
    traffic = []
    for i in range(n_req):
        tail = rng.randint(1, cfg.vocab_size - 1,
                           size=rng.randint(2, 8)).tolist()
        # ~2/3 of users share the system prompt — the prefix-cache case
        prompt = (sys_prompt + tail) if i % 3 else tail
        traffic.append((prompt, base_new + (i % 5)))
    per_wave = -(-n_req // waves)

    def quant(vals, q):
        if not vals:
            return None
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    def run(v2, spec=False):
        telemetry.reset()
        kw = {}
        if spec:
            kw.update(draft_params=params, draft_cfg=cfg, spec_k=4)
        if not v2:
            kw.update(prefix_sharing=False, prefill_rows=1,
                      chunk_size=cfg.max_seq_len)
        server = mx.serve.InferenceServer(
            params, cfg, max_batch=batch, kv_blocks=blocks,
            block_size=bs, queue_cap=n_req + 4, **kw)
        server.warmup()
        # a throwaway pass before the clock starts: first-dispatch costs
        # (executable load, backend thread pools) are process-warmth, not
        # engine throughput — without it, whichever variant runs first
        # eats them and the A/B is ordering noise
        for _ in range(2):
            server.submit(mx.serve.Request(
                rng.randint(1, cfg.vocab_size - 1, size=6).tolist(),
                max_new_tokens=4))
        server.run()
        telemetry.reset()
        handles = []
        t0 = time.perf_counter()
        for w in range(waves):
            # one burst: the whole wave lands at once, then drains
            for prompt, max_new in traffic[w * per_wave:
                                           (w + 1) * per_wave]:
                handles.append(server.submit(
                    mx.serve.Request(prompt, max_new_tokens=max_new)))
            server.run()
        # two requests that can NEVER fit: admission must shed them with a
        # structured Overloaded, not OOM the pool mid-decode
        shed = 0
        for _ in range(2):
            try:
                server.submit(mx.serve.Request(
                    [1] * 8, max_new_tokens=cfg.max_seq_len * 4))
            except mx.serve.Overloaded:
                shed += 1
        server.run()
        dt = time.perf_counter() - t0
        toks = sum(len(h.result()) for h in handles)
        return toks / dt, handles, shed

    tok_s, handles, shed = run(v2=True, spec=on_accel)
    snap = telemetry.snapshot()
    gauges = snap["gauges"]
    counters = snap["counters"]
    ttft = [h.ttft_ms for h in handles if h.ttft_ms is not None]
    tpot = [ms for h in handles for ms in h.tpot_ms]
    lookups = counters.get("serve.prefix.lookups", 0)
    tok_s_v1, handles_v1, _ = run(v2=False)
    ttft_v1 = [h.ttft_ms for h in handles_v1 if h.ttft_ms is not None]
    if on_accel:
        spec_tok_s = tok_s
        spec_counters = counters
    else:
        # the accept-rate leg: same traffic through draft/verify — the
        # mechanism metric, kept out of the CPU row's SLO columns
        spec_tok_s, _, _ = run(v2=True, spec=True)
        spec_counters = telemetry.snapshot()["counters"]
    drafted = spec_counters.get("serve.spec.drafted", 0)
    return {
        "metric": ("serve_tokens_per_sec" if on_accel
                   else "serve_cpu_tokens_per_sec"),
        "value": round(tok_s, 2),
        "unit": "tok/s",
        # vs the PR 12-shaped engine: batch-1 monolithic prefill, no
        # prefix reuse, no speculation — same traffic, same batch
        "vs_baseline": round(tok_s / tok_s_v1, 4),
        "tokens_s": round(tok_s, 2),
        "baseline_tokens_s": round(tok_s_v1, 2),
        "ttft_ms_p50": round(quant(ttft, 0.50), 3),
        "ttft_ms_p99": round(quant(ttft, 0.99), 3),
        "baseline_ttft_ms_p99": round(quant(ttft_v1, 0.99), 3),
        "tpot_ms_p50": round(quant(tpot, 0.50), 3),
        "tpot_ms_p99": round(quant(tpot, 0.99), 3),
        "accept_rate": (round(spec_counters.get("serve.spec.accepted", 0)
                              / drafted, 4) if drafted else None),
        "spec_tokens_s": round(spec_tok_s, 2),
        "prefix_hit_rate": (round(counters.get("serve.prefix.hits", 0)
                                  / lookups, 4) if lookups else None),
        "kv_blocks_saved": counters.get("serve.prefix.blocks_shared", 0),
        "prefill_chunks": counters.get("serve.prefill_chunks", 0),
        "queue_depth": gauges.get("serve.queue_depth", {}).get("max", 0),
        "shed_requests": counters.get("serve.shed", shed),
        "kv_blocks_peak": gauges.get("serve.kv.blocks_in_use",
                                     {}).get("max", 0),
        "requests": n_req,
        "recoveries": counters.get("serve.recoveries", 0),
    }


def bench_sparse(on_accel):
    """BENCH=sparse (ISSUE 17): embedding-gradient sync A/B — unique-rows
    sparse comm vs the densified-allreduce baseline, on the SAME id
    traffic. A vocab-sharded `ShardedEmbedding` trains through the
    kvstore sparse push path (row dedup -> Pallas segment-sum ->
    in-place row update) while the served lookup path answers
    row_sparse_pulls from the warmed fixed-bucket gather.

    Wire bytes are MODELED from the measured traffic (the single-process
    smoke row has no wire; the models are the exact byte accounting the
    dist store's `_sparse_sync` counters use): per step the batch's ids
    split across `world` model ranks, then

      sparse = slab x (4 + dim*4) x world      (padded all-gather slab,
                                                slab = max rank nnz)
      dense  = vocab x 4 + union x dim*4       (mask allreduce + dense
                                                union allreduce — the
                                                MXNET_TPU_SPARSE_DENSE_PUSH
                                                leg)

    so `comm_bytes_saved` is the per-run total the sparse path keeps off
    the wire — strictly positive whenever the touched fraction is small
    (the acceptance bar). value = pushed rows/s through the REAL sparse
    path; vs_baseline = dense/sparse modeled byte ratio (>1 = sparse
    wins). `lookup_ms_p50/p99` time the REAL served gather; the
    segment-sum dispatch/fallback counters prove which kernel ran."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import nd, telemetry
    from mxnet_tpu.embedding import ShardedEmbedding
    from mxnet_tpu.ndarray import sparse as sp

    vocab, dim = (1_000_000, 64) if on_accel else (50_000, 32)
    nnz, steps, world = (8192, 20, 4) if on_accel else (1024, 6, 4)
    rng = np.random.RandomState(0)

    table = ShardedEmbedding(vocab, dim, optimizer="sgd",
                             learning_rate=0.1, name="bench.sparse")
    kv = mx.kv.create("local")
    svc = kv.init_embedding(0, table, max_batch=nnz)

    # zipf-skewed traffic: the hot-row regime sparse comm exists for
    raw = rng.zipf(1.3, size=(steps, nnz)).astype(np.int64) % vocab
    batches = [np.unique(b).astype(np.int32) for b in raw]

    telemetry.reset()
    row_nb = dim * 4
    sparse_bytes = dense_bytes = pushed = 0
    union_rows = []
    lookup_ms = []
    t0 = time.perf_counter()
    for ids in batches:
        grads = rng.randn(len(ids), dim).astype(np.float32)
        kv.push(0, sp.RowSparseNDArray(grads, sp.jnp.asarray(ids),
                                       (vocab, dim)))
        # model the wire for the same traffic spread over `world` ranks
        per_rank = np.array_split(ids, world)
        slab = max(len(r) for r in per_rank)
        sparse_bytes += slab * (4 + row_nb) * world
        dense_bytes += vocab * 4 + len(ids) * row_nb
        union_rows.append(len(ids))
        pushed += len(ids)
        # served read-back of a hot subset through the compiled gather
        hot = sp.jnp.asarray(ids[:min(256, len(ids))])
        tmp = sp.zeros("row_sparse", (vocab, dim))
        t1 = time.perf_counter()
        kv.row_sparse_pull(0, out=tmp, row_ids=nd.array(hot))
        _sync(tmp._values)
        lookup_ms.append((time.perf_counter() - t1) * 1e3)
    _sync(table.weight)
    dt = time.perf_counter() - t0

    lookup_ms.sort()
    snap = telemetry.snapshot()["counters"]
    pct = 100.0 * float(np.mean(union_rows)) / vocab
    return {
        "metric": ("sparse_embed_push_rows_per_sec" if on_accel
                   else "sparse_embed_cpu_push_rows_per_sec"),
        "value": round(pushed / dt, 2),
        "unit": "rows/s",
        "vs_baseline": round(dense_bytes / sparse_bytes, 4),
        "vocab": vocab,
        "dim": dim,
        "world_model": world,
        "sparse_rows_pct": round(pct, 4),
        "comm_bytes_sparse": int(sparse_bytes),
        "comm_bytes_dense": int(dense_bytes),
        "comm_bytes_saved": int(dense_bytes - sparse_bytes),
        "lookup_ms_p50": round(lookup_ms[len(lookup_ms) // 2], 3),
        "lookup_ms_p99": round(
            lookup_ms[min(len(lookup_ms) - 1,
                          int(0.99 * len(lookup_ms)))], 3),
        "segment_sum_pallas":
            snap.get("ops.pallas.dispatch.segment_sum", 0),
        "segment_sum_fallback": sum(
            v for k, v in snap.items()
            if k.startswith("ops.pallas.fallback.segment_sum.")),
        "serve_retraces": snap.get("serve.retrace", 0),
        "unique_rows": snap.get("embedding.push.unique_rows", 0),
    }


def bench_obs(on_accel):
    """BENCH=obs: observability-plane microbench. A small Gluon MLP trains
    under the live /metrics endpoint while the bench scrapes it, measuring
    what the telemetry plane itself costs: per-scrape latency (p50/p99 µs,
    lock contention against the stepping thread included) and the rolling
    p50/p99 step latency the quantile tracker reports. value = p50 scrape
    latency; vs_baseline = scrape p50 as a fraction of step p50 (how big a
    bite one monitoring poll takes out of a step — smaller is better)."""
    import threading
    import urllib.request

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, telemetry
    from mxnet_tpu.telemetry import export

    # this bench MEASURES the telemetry plane — it cannot run disabled
    if not telemetry.ENABLED:
        print("# BENCH=obs: enabling telemetry (it is the thing under "
              "test)", file=sys.stderr)
        telemetry.enable()

    scrapes = 50
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    fused = gluon.FusedTrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer)
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(32, 16).astype(np.float32))
    y = nd.array(rng.randint(0, 8, (32,)).astype(np.float32))

    telemetry.reset()
    server = export.start_http_server(0)  # ephemeral port
    url = "http://127.0.0.1:%d/metrics" % server.port
    try:
        fused(x, y)  # compile outside the measured window
        stop = threading.Event()

        def train():
            while not stop.is_set():
                fused(x, y)

        t = threading.Thread(target=train, daemon=True)
        t.start()
        lat_us = []
        try:
            for _ in range(scrapes):
                t0 = time.perf_counter()
                urllib.request.urlopen(url, timeout=5).read()
                lat_us.append((time.perf_counter() - t0) * 1e6)
        finally:
            stop.set()
            t.join(timeout=10)
        # parity check on a QUIESCED registry (stepping thread joined, a
        # fresh scrape): counters created after the last timed scrape must
        # not read as a false exporter mismatch
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        parsed = export.parse_prometheus_text(body)
        parity = parsed == telemetry.snapshot()["counters"]
        lat_us.sort()
        p50_us = lat_us[len(lat_us) // 2]
        p99_us = lat_us[min(len(lat_us) - 1, int(0.99 * len(lat_us)))]
        q = telemetry.step_quantiles("fused_step") or {}
        step_p50_ms = q.get("p50") or float("nan")
        # federation scrape overhead: /fleet/snapshot with no peers is the
        # local-only fleet view — the fixed cost of the proxy path itself
        # (collect + merge + serialize), before any network fan-out
        fleet_url = "http://127.0.0.1:%d/fleet/snapshot" % server.port
        fleet_us = []
        for _ in range(20):
            t0 = time.perf_counter()
            urllib.request.urlopen(fleet_url, timeout=5).read()
            fleet_us.append((time.perf_counter() - t0) * 1e6)
        fleet_us.sort()
        return {
            "metric": ("obs_scrape_p50_us" if on_accel
                       else "obs_cpu_scrape_p50_us"),
            "value": round(p50_us, 1),
            "unit": "us",
            "vs_baseline": round(p50_us / (step_p50_ms * 1e3), 4)
            if step_p50_ms == step_p50_ms else None,
            "scrape_p99_us": round(p99_us, 1),
            "scrape_parity": bool(parity),
            "step_ms_p50": round(q.get("p50", 0.0), 3),
            "step_ms_p99": round(q.get("p99", 0.0), 3),
            "scrapes": len(lat_us),
            "fleet_scrape_p50_us": round(fleet_us[len(fleet_us) // 2], 1),
            **_bench_request_trace_overhead(),
            **_bench_ledger_overhead(),
        }
    finally:
        export.stop_http_server()


def _bench_request_trace_overhead():
    """Per-request tracing overhead (the ISSUE 12 acceptance ceiling:
    <= 2% of serve tokens/s): the same tiny-llama traffic served with
    request tracing ON (default) and OFF (MXNET_TPU_SERVE_TRACE=0);
    reports both rates and the relative cost."""
    import dataclasses

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.models.llama import CONFIGS, llama_init

    cfg = dataclasses.replace(CONFIGS["llama_tiny"], dtype=jnp.float32,
                              max_seq_len=64)
    params = llama_init(jax.random.PRNGKey(0), cfg)

    def run(trace_on):
        prev = os.environ.get("MXNET_TPU_SERVE_TRACE")
        os.environ["MXNET_TPU_SERVE_TRACE"] = "1" if trace_on else "0"
        try:
            telemetry.reset()
            server = mx.serve.InferenceServer(
                params, cfg, max_batch=4, kv_blocks=64, block_size=8,
                max_context=48, queue_cap=32)
            server.warmup()
            rng = np.random.RandomState(0)
            prompts = [rng.randint(1, cfg.vocab_size - 1,
                                   size=rng.randint(4, 12)).tolist()
                       for _ in range(10)]
            handles = [server.submit(mx.serve.Request(p, max_new_tokens=16))
                       for p in prompts]
            t0 = time.perf_counter()
            server.run()
            dt = time.perf_counter() - t0
            toks = sum(len(h.result(timeout=60)) for h in handles)
            return toks / dt
        finally:
            if prev is None:
                os.environ.pop("MXNET_TPU_SERVE_TRACE", None)
            else:
                os.environ["MXNET_TPU_SERVE_TRACE"] = prev

    # cold-start and scheduling noise on the CPU smoke row dwarfs the
    # per-token mark cost: warm both modes once, then interleave pairs
    # and compare MEDIANS (the first measured attempt was order-biased
    # by a cold first run)
    import statistics
    run(True)
    run(False)
    traced_runs, untraced_runs = [], []
    for i in range(3):
        if i % 2 == 0:
            traced_runs.append(run(True))
            untraced_runs.append(run(False))
        else:
            untraced_runs.append(run(False))
            traced_runs.append(run(True))
    traced = statistics.median(traced_runs)
    untraced = statistics.median(untraced_runs)
    return {
        "serve_tok_s_traced": round(traced, 2),
        "serve_tok_s_untraced": round(untraced, 2),
        "request_trace_overhead_pct": round(
            max(0.0, (untraced - traced) / untraced * 100.0), 3),
    }


def _bench_ledger_overhead():
    """HBM-ledger + profiling-plane overhead (the ISSUE 16 acceptance
    ceiling: <= 2% of serve tokens/s): the same tiny-llama traffic served
    with the memory ledger ON (default) and OFF (MXNET_TPU_LEDGER=0 —
    every ledger.account()/reconcile at the KV pool, prefix cache, and
    program-footprint sites goes quiet). Same interleaved-medians shape
    as _bench_request_trace_overhead: cold-start noise on the CPU smoke
    row dwarfs the per-admit accounting cost, so warm both modes first
    and compare medians of interleaved pairs. Each run serves enough
    tokens (~0.2 s on the CPU smoke row) that the once-per-second
    reconcile amortizes the way it does in a real serve process — a
    40 ms burst charges the whole 1.4 ms live_arrays scan to one run
    and reads as a fake 3% regression."""
    import dataclasses
    import statistics

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.models.llama import CONFIGS, llama_init

    cfg = dataclasses.replace(CONFIGS["llama_tiny"], dtype=jnp.float32,
                              max_seq_len=64)
    params = llama_init(jax.random.PRNGKey(0), cfg)

    def run(ledger_on):
        prev = os.environ.get("MXNET_TPU_LEDGER")
        os.environ["MXNET_TPU_LEDGER"] = "1" if ledger_on else "0"
        try:
            telemetry.reset()
            server = mx.serve.InferenceServer(
                params, cfg, max_batch=4, kv_blocks=64, block_size=8,
                max_context=48, queue_cap=32)
            server.warmup()
            rng = np.random.RandomState(0)
            prompts = [rng.randint(1, cfg.vocab_size - 1,
                                   size=rng.randint(4, 12)).tolist()
                       for _ in range(24)]
            handles = [server.submit(mx.serve.Request(p, max_new_tokens=32))
                       for p in prompts]
            t0 = time.perf_counter()
            server.run()
            dt = time.perf_counter() - t0
            toks = sum(len(h.result(timeout=60)) for h in handles)
            return toks / dt
        finally:
            if prev is None:
                os.environ.pop("MXNET_TPU_LEDGER", None)
            else:
                os.environ["MXNET_TPU_LEDGER"] = prev

    run(True)
    run(False)
    on_runs, off_runs = [], []
    for i in range(3):
        if i % 2 == 0:
            on_runs.append(run(True))
            off_runs.append(run(False))
        else:
            off_runs.append(run(False))
            on_runs.append(run(True))
    with_ledger = statistics.median(on_runs)
    without = statistics.median(off_runs)
    return {
        "serve_tok_s_ledger": round(with_ledger, 2),
        "serve_tok_s_no_ledger": round(without, 2),
        "ledger_overhead_pct": round(
            max(0.0, (without - with_ledger) / without * 100.0), 3),
    }


def _probe_backend(timeout=240):
    """Initialize the default backend with a hang guard. The axon PjRt
    tunnel blocks indefinitely in make_c_api_client when the relay is
    down (observed for the whole 2026-07-30 session); a bench run must
    then fall back to an HONESTLY-NAMED cpu smoke row instead of hanging
    until the driver kills it (rc!=0, no data at all).

    The probe runs in a SUBPROCESS: an in-process probe thread that
    hangs in backend init would hold jax's global backend lock forever,
    deadlocking the cpu fallback too. The probe child gets its own
    process group (killpg on timeout — a tunnel helper grandchild
    holding the stdout pipe would otherwise hang the guard itself), and
    the parent's real init runs under a hard watchdog so a relay that
    flaps between probe and init exits promptly with a diagnosis
    instead of reproducing the indefinite hang."""
    import os as _os
    import signal
    import subprocess
    import threading

    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices(); print('up')"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
        up = proc.returncode == 0 and "up" in (out or "")
        reason = "probe rc=%s" % proc.returncode
    except subprocess.TimeoutExpired:
        up = False
        reason = "timeout after %ds" % timeout
        try:
            _os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        proc.communicate()
    if up:
        # the backend was reachable moments ago; guard the real init
        # against a flap in between (rc 3 beats an eternal hang)
        watchdog = threading.Timer(120, lambda: (
            print("# backend flapped between probe and init — aborting",
                  file=sys.stderr), _os._exit(3)))
        watchdog.daemon = True
        watchdog.start()
        try:
            return jax.devices()[0]
        finally:
            watchdog.cancel()
    print("# accelerator backend unreachable (%s) — falling back to cpu"
          % reason, file=sys.stderr)
    global _FELL_BACK
    _FELL_BACK = True
    jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0]


def bench_startup_child():
    """The measured body of BENCH=startup, run in a fresh subprocess: the
    program-build work a replica pays at boot — a symbolic Module bind +
    whole-graph training forward, and an mx.serve warmup() (chunk
    prefill + decode + CoW copy). With a warm MXNET_TPU_AOT_CACHE every
    one of these executables restores from disk: compile_count drops to 0
    and cache_hits counts the restored programs. Prints ONE JSON line.
    (`tools/prebake_cache.py` drives the same warmup from a manifest to
    pre-populate a fleet's shared cache.)"""
    t0 = time.perf_counter()
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import symbol as sym, telemetry
    from mxnet_tpu.io.io import DataBatch
    from mxnet_tpu.models.llama import LlamaConfig, llama_init
    from mxnet_tpu.serve.kv_cache import KVBlockPool
    from mxnet_tpu.serve.programs import ServePrograms

    # 1) symbolic path: bind + one whole-graph forward+backward program
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, name="fc1", num_hidden=32)
    act = sym.Activation(fc1, name="relu1", act_type="relu")
    fc2 = sym.FullyConnected(act, name="fc2", num_hidden=8)
    net = sym.SoftmaxOutput(fc2, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (8, 16))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params()
    rng = np.random.RandomState(0)
    batch = DataBatch([mx.nd.array(rng.rand(8, 16).astype(np.float32))],
                      [mx.nd.array(rng.randint(0, 8, (8,))
                                   .astype(np.float32))])
    mod.forward(batch, is_train=True)
    mod.backward()

    # 2) serving path: every warmup executable a replica needs
    cfg = LlamaConfig(vocab_size=128, dim=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=64, rope_theta=10000.0,
                      max_seq_len=32)
    import jax
    params = llama_init(jax.random.PRNGKey(0), cfg)
    pool = KVBlockPool(cfg, num_blocks=16, block_size=8)
    ServePrograms(params, cfg, pool, max_batch=2, max_context=16).warmup()

    c = telemetry.snapshot()["counters"]
    print(json.dumps({
        "startup_s": round(time.perf_counter() - t0, 4),
        "compile_count": (c.get("compiler.compile", 0)
                          + c.get("serve.compile", 0)),
        "cache_hits": c.get("compiler.cache.hits", 0),
        "cache_misses": c.get("compiler.cache.misses", 0),
        "cache_writes": c.get("compiler.cache.writes", 0),
        "fallbacks": c.get("compiler.fallback", 0),
    }))


def bench_startup(on_accel):
    """BENCH=startup (ISSUE 11): cold vs warm-AOT-cache process start.
    Spawns the same child workload twice against ONE fresh cache
    directory — the first run compiles and writes, the second must
    restore every executable (compile_count 0, cache_hits > 0). A
    pre-set MXNET_TPU_AOT_CACHE is deliberately ignored: the cold child
    must actually be cold, or the row measures a warm restore twice.
    value = the warm child's program-build seconds; vs_baseline =
    cold/warm build-time ratio (how many times faster a fleet replica
    boots once one sibling has paid the compiles)."""
    import shutil
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="mx_aot_startup_")
    env = dict(os.environ, BENCH="startup_child",
               MXNET_TPU_AOT_CACHE=cache_dir)

    def child(tag):
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            capture_output=True, text=True, timeout=900)
        wall = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError("startup child (%s) failed:\n%s"
                               % (tag, proc.stderr[-2000:]))
        row = json.loads(
            [ln for ln in proc.stdout.splitlines()
             if ln.startswith("{")][-1])
        row["process_wall_s"] = round(wall, 3)
        return row

    try:
        cold = child("cold")
        warm = child("warm")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return {
        "metric": "startup_warm_s",
        "value": warm["startup_s"],
        "unit": "s",
        "startup_cold_s": cold["startup_s"],
        "startup_warm_s": warm["startup_s"],
        "process_wall_cold_s": cold["process_wall_s"],
        "process_wall_warm_s": warm["process_wall_s"],
        "compile_count_cold": cold["compile_count"],
        "compile_count_warm": warm["compile_count"],
        "cache_hits_warm": warm["cache_hits"],
        "cache_writes_cold": cold["cache_writes"],
        "vs_baseline": round(cold["startup_s"]
                             / max(warm["startup_s"], 1e-9), 4),
    }


def main():
    dev = _probe_backend()
    on_accel = dev.platform != "cpu"
    which = os.environ.get("BENCH", "gluon")
    if which == "startup_child":
        bench_startup_child()
        return
    if which == "startup":
        _emit(bench_startup(on_accel))
        return
    if which in ("fused", "fused_train"):
        os.environ.setdefault("MXNET_TPU_USE_PALLAS", "1")
        if not on_accel:
            os.environ.setdefault("MXNET_FLASH_INTERPRET", "1")
        bench_fn = (bench_fused_stage if which == "fused"
                    else bench_fused_train_stage)
        fast, base = bench_fn(on_accel)
        name = ("fused_conv_bn_relu" if which == "fused"
                else "fused_conv_bn_relu_train")
        _emit({
            "metric": ("%s_img_per_sec" % name if on_accel
                       else "%s_cpu_img_per_sec" % name),
            "value": round(fast, 2),
            "unit": "img/s",
            "vs_baseline": round(fast / base, 4),   # vs XLA composed
        })
        return
    if which in ("fused_bwd", "fused_opt"):
        os.environ.setdefault("MXNET_TPU_USE_PALLAS", "1")
        if not on_accel:
            os.environ.setdefault("MXNET_FLASH_INTERPRET", "1")
        _emit((bench_fused_bwd if which == "fused_bwd"
               else bench_fused_opt)(on_accel))
        return
    if which == "comm":
        _emit(bench_comm(on_accel))
        for row in bench_comm_readiness(on_accel):
            _emit(row)
        return
    if which == "zero":
        _emit(bench_zero(on_accel))
        return
    if which == "sparse":
        os.environ.setdefault("MXNET_TPU_USE_PALLAS", "1")
        if not on_accel:
            os.environ.setdefault("MXNET_FLASH_INTERPRET", "1")
        _emit(bench_sparse(on_accel))
        return
    if which == "resilience":
        _emit(bench_resilience(on_accel))
        return
    if which == "obs":
        _emit(bench_obs(on_accel))
        return
    if which == "serve":
        _emit(bench_serve(on_accel))
        return
    if which in ("bert", "bert_gluon"):
        tok_s, _ = (bench_bert if which == "bert"
                    else bench_bert_gluon)(on_accel)
        bert_bar = 126720.0
        name = ("bert_base_train_tok_per_sec" if on_accel
                else "bert_tiny_cpu_tok_per_sec")
        if which == "bert_gluon":
            name = name.replace("tok_per_sec", "gluon_tok_per_sec")
        _emit({
            "metric": name,
            "value": round(tok_s, 2),
            "unit": "tok/s",
            "vs_baseline": round(tok_s / bert_bar, 4),
        })
        return
    if which == "functional":
        img_s, path = bench_functional(on_accel)
    elif which == "gluon_nhwc":
        img_s, path = bench_gluon(on_accel, layout="NHWC")
        path = "gluon_nhwc"
    elif which == "gluon_fused":
        # the full headline model with the TRAINING-form fused
        # conv+BN+ReLU blocks in every bottleneck (ROOFLINE round-5)
        os.environ["MXNET_TPU_FUSED_CONVBN"] = "1"
        os.environ.setdefault("MXNET_TPU_USE_PALLAS", "1")
        if not on_accel:
            os.environ.setdefault("MXNET_FLASH_INTERPRET", "1")
        img_s, path = bench_gluon(on_accel, layout="NHWC")
        path = "gluon_fused"
    else:
        # the chip-capture watcher promotes NHWC to the headline default
        # once a live window showed it clears the bar AND beats NCHW
        # (tools/chip_capture.py maybe_promote_nhwc). MXNET_HEADLINE_LAYOUT
        # overrides the marker (the capture's baseline row must stay NCHW).
        marker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "chip_artifacts", "NHWC_PROMOTE")
        # on_accel gate: the cpu smoke row must stay NCHW so its metric
        # stays comparable with every historical cpu row
        layout = os.environ.get(
            "MXNET_HEADLINE_LAYOUT",
            "NHWC" if on_accel and os.path.exists(marker) else "NCHW")
        if layout == "NHWC":
            print("# headline layout: NHWC (promoted by chip capture)",
                  file=sys.stderr)
        img_s, path = bench_gluon(on_accel, layout=layout)
    if on_accel:
        name = "resnet50_train_img_per_sec"
        if path != "gluon":
            name += "_" + path
    else:
        # CPU smoke paths measure different tiny models — name them honestly
        # (round-1 key kept for the functional config)
        name = ("resnet_tiny_cpu_img_per_sec" if path == "functional"
                else "resnet18_cpu_%s_img_per_sec" % path)
    _emit({
        "metric": name,
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    })


if __name__ == "__main__":
    main()
