"""Headline benchmark: ResNet-50 training throughput (images/sec/chip).

BASELINE.md: target >= 0.9x A100 per-chip throughput. A100 ResNet-50 train
(fp16/AMP, batch 256) is ~2500 img/s, so vs_baseline is measured against
0.9 * 2500 = 2250 img/s. Synthetic data, bf16, fused fwd+bwd+SGD step per
the BASELINE.md measurement protocol (warm-up, then median-free steady-state
mean over 50 steps).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s", "vs_baseline": N}
"""
import json
import time

import jax
import jax.numpy as jnp

from mxnet_tpu.models.resnet import (CONFIGS, resnet_init, resnet_loss,
                                     update_running_stats)

BASELINE_IMG_S = 2250.0
LR = 0.1
MOMENTUM = 0.9


def tmap(f, *t):
    return jax.tree_util.tree_map(f, *t)


def main():
    dev = jax.devices()[0]
    on_accel = dev.platform != "cpu"
    cfg = CONFIGS["resnet50"] if on_accel else CONFIGS["resnet_tiny"]
    batch = 256 if on_accel else 8
    size = 224 if on_accel else 32
    steps, warmup = (50, 10) if on_accel else (5, 2)

    key = jax.random.PRNGKey(0)
    params = resnet_init(key, cfg)
    mom = tmap(jnp.zeros_like, params)
    images = jax.random.normal(jax.random.PRNGKey(1),
                               (batch, size, size, 3), jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0,
                                cfg.classes)
    data = {"images": images, "labels": labels}

    @jax.jit
    def step(params, mom, data):
        (loss, stats), grads = jax.value_and_grad(
            resnet_loss, has_aux=True)(params, data, cfg)
        mom = tmap(lambda m, g: MOMENTUM * m + g.astype(m.dtype), mom, grads)
        params = tmap(lambda p, m: (p - LR * m.astype(p.dtype)).astype(p.dtype),
                      params, mom)
        params = update_running_stats(params, stats, cfg)
        return params, mom, loss

    for _ in range(warmup):
        params, mom, loss = step(params, mom, data)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, mom, loss = step(params, mom, data)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_s = batch * steps / dt
    print(json.dumps({
        "metric": "resnet50_train_img_per_sec" if on_accel
                  else "resnet_tiny_cpu_img_per_sec",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }))


if __name__ == "__main__":
    main()
