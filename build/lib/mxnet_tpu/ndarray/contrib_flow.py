"""Control-flow contrib ops: foreach / while_loop / cond.

reference: src/operator/control_flow.cc + python/mxnet/ndarray/contrib.py
(foreach, while_loop, cond) — the reference's dynamic-model building
blocks. Two regimes, exactly like the reference:

* imperative (eager NDArrays): a Python loop / branch call, so the
  autograd tape records every op — gradients flow to any NDArray the body
  closes over, and `while_loop` runs its true dynamic trip count;
* traced (inside hybridize()/jit, payloads are tracers): `foreach` IS
  `lax.scan`, `while_loop` IS `lax.while_loop` over a
  max_iterations-sized buffer, `cond` IS `lax.cond` — compiled control
  flow, not an unrolled graph (the reference's C++ subgraph ops made the
  same move).

For shape stability across both regimes, `while_loop` always returns a
(max_iterations, ...) output buffer, zero-padded past the trip count —
the reference's symbolic-mode convention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .ndarray import NDArray, from_jax, _is_tracer
from ..context import current_context

__all__ = ["foreach", "while_loop", "cond"]


def _wrap(x, ctx):
    return from_jax(x, ctx=ctx) if not isinstance(x, NDArray) else x


def _unwrap(x):
    return x._read() if isinstance(x, NDArray) else jnp.asarray(x)


def _map_unwrap(tree):
    if isinstance(tree, (list, tuple)):
        return type(tree)(_map_unwrap(t) for t in tree)
    return _unwrap(tree)


def _map_wrap(tree, ctx):
    if isinstance(tree, (list, tuple)):
        return type(tree)(_map_wrap(t, ctx) for t in tree)
    return _wrap(tree, ctx)


def _any_tracer(tree):
    if isinstance(tree, (list, tuple)):
        return any(_any_tracer(t) for t in tree)
    return _is_tracer(_unwrap(tree))


def foreach(body, data, init_states):
    """Scan `body(data_slice, states) -> (outputs, new_states)` along
    axis 0 of `data`; returns (stacked_outputs, final_states).
    reference: contrib.foreach."""
    ctx = current_context()
    if _any_tracer(data) or _any_tracer(init_states):
        data_raw = _map_unwrap(data)
        states_raw = _map_unwrap(init_states)

        def scan_body(carry, x):
            out, new_states = body(_map_wrap(x, ctx), _map_wrap(carry, ctx))
            return _map_unwrap(new_states), _map_unwrap(out)

        final_raw, outs_raw = lax.scan(scan_body, states_raw, data_raw)
        return _map_wrap(outs_raw, ctx), _map_wrap(final_raw, ctx)

    # imperative: python loop — every body op lands on the autograd tape
    from . import stack as _nd_stack
    n = (data.shape[0] if isinstance(data, NDArray)
         else data[0].shape[0])
    states = init_states
    outs = []
    for i in range(n):
        x = data[i] if isinstance(data, NDArray) else \
            type(data)(d[i] for d in data)
        out, states = body(x, states)
        outs.append(out)
    if not outs:   # T == 0: empty buffer, like the lax.scan path
        out_shapes = jax.eval_shape(
            lambda d, st: _map_unwrap(body(_map_wrap(d, ctx),
                                           _map_wrap(st, ctx))[0]),
            _map_unwrap(data[0] if isinstance(data, NDArray)
                        else type(data)(d[0] for d in data))
            if n else jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape[1:], a.dtype),
                _map_unwrap(data)),
            _map_unwrap(init_states))
        empty = jax.tree_util.tree_map(
            lambda sh: from_jax(jnp.zeros((0,) + sh.shape, sh.dtype),
                                ctx=ctx), out_shapes)
        return empty, states
    if isinstance(outs[0], (list, tuple)):
        stacked = type(outs[0])(
            _nd_stack(*[o[j] for o in outs], axis=0)
            for j in range(len(outs[0])))
    else:
        stacked = _nd_stack(*outs, axis=0)
    return stacked, states


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """`while cond_fn(*loop_vars): outputs, loop_vars = func(*loop_vars)`.
    Returns (stacked_outputs, final_loop_vars); outputs live in a
    (max_iterations, ...) buffer zero-padded past the trip count.
    reference: contrib.while_loop."""
    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations (static "
                         "shapes: the output buffer must be allocated "
                         "before tracing)")
    ctx = current_context()
    loop_vars = tuple(loop_vars) if isinstance(loop_vars, (list, tuple)) \
        else (loop_vars,)
    if _any_tracer(loop_vars):
        vars_raw = _map_unwrap(loop_vars)
        out_shapes = jax.eval_shape(
            lambda vr: _map_unwrap(func(*_map_wrap(vr, ctx))[0]), vars_raw)
        out_buf = jax.tree_util.tree_map(
            lambda s: jnp.zeros((max_iterations,) + s.shape, s.dtype),
            out_shapes)

        def cond_wrap(state):
            i, buf, vr = state
            c = _unwrap(cond_fn(*_map_wrap(vr, ctx)))
            return jnp.logical_and(i < max_iterations,
                                   c.reshape(()).astype(bool))

        def body_wrap(state):
            i, buf, vr = state
            out, new_vars = func(*_map_wrap(vr, ctx))
            if not isinstance(new_vars, (list, tuple)):
                new_vars = (new_vars,)
            new_vars = tuple(new_vars)
            out_raw = _map_unwrap(out)
            buf = jax.tree_util.tree_map(
                lambda b, o: lax.dynamic_update_index_in_dim(b, o, i, 0),
                buf, out_raw)
            return i + 1, buf, _map_unwrap(new_vars)

        _, buf, final_raw = lax.while_loop(
            cond_wrap, body_wrap, (jnp.int32(0), out_buf, vars_raw))
        return _map_wrap(buf, ctx), _map_wrap(final_raw, ctx)

    # imperative: true dynamic trip count; pad with zeros via nd ops so
    # the result shape matches the traced regime
    from . import stack as _nd_stack, zeros_like as _nd_zeros_like
    vars_ = tuple(loop_vars)
    outs = []
    steps = 0
    while steps < max_iterations and bool(
            _unwrap(cond_fn(*vars_)).reshape(())):
        out, new_vars = func(*vars_)
        vars_ = tuple(new_vars) if isinstance(new_vars, (list, tuple)) \
            else (new_vars,)
        outs.append(out)
        steps += 1
    if not outs:   # zero trips: zero buffer, same as the traced regime
        out_shapes = jax.eval_shape(
            lambda vr: _map_unwrap(func(*_map_wrap(vr, ctx))[0]),
            _map_unwrap(vars_))
        zero = jax.tree_util.tree_map(
            lambda sh: from_jax(
                jnp.zeros((max_iterations,) + sh.shape, sh.dtype), ctx=ctx),
            out_shapes)
        return zero, vars_

    def pad_stack(slices):
        pad = [_nd_zeros_like(slices[-1])] * (max_iterations - len(slices))
        return _nd_stack(*(list(slices) + pad), axis=0)

    if isinstance(outs[0], (list, tuple)):
        stacked = type(outs[0])(
            pad_stack([o[j] for o in outs]) for j in range(len(outs[0])))
    else:
        stacked = pad_stack(outs)
    return stacked, vars_


def cond(pred, then_func, else_func, inputs=()):
    """`then_func(*inputs)` when pred else `else_func(*inputs)`.
    Imperatively only the taken branch runs (reference behavior); under
    tracing both branches compile into one `lax.cond`.
    reference: contrib.cond."""
    ctx = current_context()
    if _any_tracer(pred) or _any_tracer(tuple(inputs)):
        pred_raw = _unwrap(pred).reshape(()).astype(bool)
        in_raw = _map_unwrap(tuple(inputs))

        def mk(fn):
            def br(raws):
                return _map_unwrap(fn(*_map_wrap(raws, ctx)))
            return br

        out_raw = lax.cond(pred_raw, mk(then_func), mk(else_func), in_raw)
        return _map_wrap(out_raw, ctx)
    taken = then_func if bool(_unwrap(pred).reshape(())) else else_func
    return taken(*inputs)
