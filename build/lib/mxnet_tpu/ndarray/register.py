"""Code-generate the `mx.nd.*` namespace from the op registry.

TPU-native analog of the reference's import-time codegen (reference:
python/mxnet/ndarray/register.py — introspects the C op registry via
MXSymbolListAtomicSymbolCreators and emits one Python function per op). Here
the registry is Python-side, so generation is a loop over
`ops.registry.list_ops()`.
"""
from __future__ import annotations

from ..ops import registry as _reg
from .ndarray import invoke


def make_op_func(name):
    op = _reg.get(name)

    def op_func(*args, out=None, **kwargs):
        return invoke(name, *args, out=out, **kwargs)

    op_func.__name__ = name.lstrip("_") or name
    op_func.__qualname__ = op_func.__name__
    op_func.__doc__ = op.doc or ("%s (auto-generated from the op registry)" % name)
    return op_func


def populate(namespace, names=None):
    for name in (names or _reg.list_ops()):
        namespace.setdefault(name, make_op_func(name))
    return namespace
