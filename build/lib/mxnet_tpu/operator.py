"""`mx.operator` — the CustomOp extension bridge.

reference: python/mxnet/operator.py (CustomOp, CustomOpProp, register) and
src/operator/custom/custom.cc. The reference runs python callbacks on a
dedicated worker thread behind the engine; here the callback simply runs
eagerly on the host (JAX dispatch is already async around it) and its
backward is recorded on the autograd tape like any other op. Outputs of a
Custom op are host-computed NDArrays — the escape hatch the reference
provides for "not expressible in the op library", at the same cost profile
(host sync per call).

Usage (identical to the reference):

    @mx.operator.register("softsign")
    class SoftsignProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)
        def list_arguments(self):
            return ['data']
        def list_outputs(self):
            return ['output']
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []
        def create_operator(self, ctx, shapes, dtypes):
            return Softsign()

    class Softsign(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], x / (1 + abs(x)))
        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            ...

    y = mx.nd.Custom(x, op_type='softsign')
"""
from __future__ import annotations

import numpy as _np

from . import autograd
from .base import MXNetError
from .context import current_context

__all__ = ["CustomOp", "CustomOpProp", "register", "get_entry"]

_CUSTOM_REGISTRY = {}


class CustomOp:
    """Base class for the user's kernel. reference: operator.py (CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write `src` into `dst` honoring the grad_req (reference:
        CustomOp.assign — 'null' skip, 'write'/'inplace' overwrite,
        'add' accumulate)."""
        if req == "null":
            return
        from .ndarray.ndarray import NDArray
        if not isinstance(src, NDArray):
            src = NDArray(src) if hasattr(src, "dtype") else \
                NDArray(_np.asarray(src))
        if req in ("write", "inplace"):
            dst._write(src._read().astype(dst.dtype))
        elif req == "add":
            dst._write((dst._read() + src._read()).astype(dst.dtype))
        else:
            raise MXNetError("unknown req %r" % req)


class CustomOpProp:
    """Shape/type metadata + operator factory.
    reference: operator.py (CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = bool(need_top_grad)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        t = in_type[0]
        return (in_type, [t] * len(self.list_outputs()),
                [t] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Class decorator registering a CustomOpProp under `op_type`.
    reference: mx.operator.register."""
    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls
    return deco


def get_entry(op_type):
    prop_cls = _CUSTOM_REGISTRY.get(op_type)
    if prop_cls is None:
        raise MXNetError(
            "Custom op %r is not registered (mx.operator.register)" % op_type)
    return prop_cls


def invoke_custom(*inputs, op_type=None, **kwargs):
    """Execute a registered custom op imperatively — the body of
    `mx.nd.Custom` (reference: custom.cc Forward/Backward dispatch)."""
    from .ndarray.ndarray import NDArray, zeros

    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    prop = get_entry(op_type)(**{k: str(v) for k, v in kwargs.items()}) \
        if _prop_takes_kwargs(get_entry(op_type), kwargs) else \
        get_entry(op_type)()
    n_args = len(prop.list_arguments())
    n_aux = len(prop.list_auxiliary_states())
    if len(inputs) != n_args + n_aux:
        raise MXNetError(
            "Custom %r expects %d inputs (+%d aux), got %d"
            % (op_type, n_args, n_aux, len(inputs)))
    in_data = list(inputs[:n_args])
    aux = list(inputs[n_args:])
    ctx = in_data[0].context if in_data else current_context()

    in_shapes = [list(a.shape) for a in in_data]
    ishapes, oshapes, ashapes = prop.infer_shape(in_shapes)
    itypes, otypes, atypes = prop.infer_type(
        [a.dtype for a in in_data])
    op = prop.create_operator(ctx, ishapes, itypes)

    out_data = [zeros(tuple(s), ctx=ctx, dtype=t)
                for s, t in zip(oshapes, otypes)]
    with autograd.pause():
        op.forward(autograd.is_training(), ["write"] * len(out_data),
                   in_data, out_data, aux)

    if autograd.is_recording():
        n_out = len(out_data)

        def vjp_fn(cot):
            cots = (cot,) if n_out == 1 else cot
            out_grad = [NDArray(c, ctx=ctx) for c in cots]
            in_grad = [zeros(a.shape, ctx=ctx, dtype=a.dtype)
                       for a in in_data]
            with autograd.pause():
                op.backward(["write"] * len(in_grad), out_grad, in_data,
                            out_data, in_grad, aux)
            return [g._read() for g in in_grad]

        autograd.record_op("Custom:%s" % op_type, in_data, out_data, vjp_fn)
    return out_data[0] if len(out_data) == 1 else out_data


def _prop_takes_kwargs(prop_cls, kwargs):
    if not kwargs:
        return False
    import inspect
    sig = inspect.signature(prop_cls.__init__)
    return len(sig.parameters) > 1
