"""mxnet_tpu.parallel — TPU-native parallelism subsystem.

The reference scales via KVStore backends (src/kvstore/: CommDevice NVLink
reduce, KVStoreNCCL ring allreduce, ps-lite parameter server over ZMQ) plus a
manual `group2ctx` model-parallel primitive (src/executor/graph_executor.cc).
The TPU-native answer is one unified mechanism: a `jax.sharding.Mesh` over the
chip topology, `NamedSharding`/`PartitionSpec` annotations on parameters and
activations, and XLA-inserted collectives riding ICI (intra-slice) / DCN
(cross-slice). This package holds that machinery:

* mesh.py         — mesh construction/current-mesh scoping (`MeshConfig`)
* sharding.py     — Megatron/FSDP-style per-parameter PartitionSpec rules
* collectives.py  — psum/all_gather/ppermute/reduce_scatter wrappers + comm bench
* dist.py         — multi-controller init (jax.distributed) with DMLC_* env compat
* flash_attention.py — fused attention kernel (Pallas on TPU, lax fallback)
* ring_attention.py  — sequence-parallel ring attention over a mesh axis
* train_step.py   — compile a whole train step (fwd+bwd+opt) under shardings
"""
from .mesh import (MeshConfig, create_mesh, current_mesh, local_mesh,
                   mesh_scope, auto_mesh)
from .sharding import (ShardingRules, LLAMA_RULES, BERT_RULES,
                       named_sharding, shard_pytree, replicate_pytree,
                       logical_to_spec)
from .collectives import (all_reduce, all_gather, reduce_scatter, ppermute,
                          barrier, allreduce_bench)
from .dist import initialize, is_initialized, rank, num_workers
from .flash_attention import flash_attention
from .ring_attention import ring_attention
from .train_step import ShardedTrainStep
from .checkpoint import (save_sharded, restore_sharded, latest_step,
                         save_train_state, restore_train_state)

__all__ = [
    "MeshConfig", "create_mesh", "current_mesh", "local_mesh", "mesh_scope",
    "auto_mesh", "ShardingRules", "LLAMA_RULES", "BERT_RULES",
    "named_sharding", "shard_pytree", "replicate_pytree", "logical_to_spec",
    "all_reduce", "all_gather", "reduce_scatter", "ppermute", "barrier",
    "allreduce_bench", "initialize", "is_initialized", "rank", "num_workers",
    "flash_attention", "ring_attention", "ShardedTrainStep",
]
