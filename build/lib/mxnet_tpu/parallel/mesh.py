"""Device-mesh construction and scoping.

Replaces the reference's device-list plumbing (`Module(context=[gpu(0),...])`,
`kvstore 'device'` comm topology in src/kvstore/comm.h) with a named
`jax.sharding.Mesh`. A mesh axis name is the unit of parallelism: 'data' for
DP, 'model' for TP, 'seq' for sequence/context parallelism, 'expert' for MoE.
"""
from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass, field

import numpy as _np

import jax
from jax.sharding import Mesh, PartitionSpec

__all__ = ["MeshConfig", "create_mesh", "local_mesh", "auto_mesh",
           "current_mesh", "mesh_scope"]

_STATE = threading.local()


@dataclass
class MeshConfig:
    """Declarative mesh shape. Axes with size 1 are kept (harmless) so
    PartitionSpecs can always name them.

    data:  data-parallel (batch) axis — gradients psum over this.
    fsdp:  parameter-sharding axis (ZeRO-3 / FSDP); params all-gathered
           per-layer on use. Merged with `data` for plain DP when 1.
    model: tensor-parallel axis (Megatron column/row splits).
    seq:   sequence/context-parallel axis (ring attention).
    expert: expert-parallel axis (MoE all_to_all).
    """
    data: int = 1
    fsdp: int = 1
    model: int = 1
    seq: int = 1
    expert: int = 1
    axis_order: tuple = ("data", "fsdp", "seq", "model", "expert")

    def sizes(self):
        return tuple(getattr(self, a) for a in self.axis_order)

    @property
    def n_devices(self):
        n = 1
        for s in self.sizes():
            n *= s
        return n


def _default_devices(n_needed):
    """Default device list for a mesh that needs `n_needed` devices.

    When MXNET_MESH_HOST_FALLBACK=1 (set by the on-chip test harness,
    tests/conftest.py) and the default backend has fewer devices than the
    mesh needs — e.g. a single real chip vs an 8-way mesh test — fall
    back to the virtual host-CPU devices so multi-device code paths still
    execute. Production code never sets the gate: too few devices stays
    a hard error."""
    devices = jax.devices()
    if (len(devices) < n_needed
            and os.environ.get("MXNET_MESH_HOST_FALLBACK", "0") == "1"):
        try:
            host = jax.devices("cpu")
        except RuntimeError:
            return devices
        if len(host) >= n_needed:
            return host
    return devices


def create_mesh(config=None, devices=None, **axes):
    """Build a Mesh from a MeshConfig or axis kwargs.

    ``create_mesh(data=4, model=2)`` → 8-device mesh with axes
    ('data','fsdp','seq','model','expert') sized (4,1,1,2,1). ICI-friendly:
    axis order puts 'model' innermost-but-one so TP collectives ride
    nearest-neighbor links.
    """
    if config is None:
        config = MeshConfig(**axes)
    n = config.n_devices
    if devices is None:
        devices = _default_devices(n)
    if n > len(devices):
        raise ValueError(
            "mesh needs %d devices but only %d available" % (n, len(devices)))
    dev_array = _np.asarray(devices[:n]).reshape(config.sizes())
    return Mesh(dev_array, config.axis_order)


def local_mesh(n_devices=None, axis="data"):
    """1-D mesh over (the first n) local devices — the analog of the
    reference's single-process multi-GPU `kvstore='device'` setup."""
    devices = _default_devices(n_devices or 1)
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(_np.asarray(devices), (axis,))


def auto_mesh(model_parallel=1, seq_parallel=1, fsdp=False):
    """Pick a sensible mesh for all visible devices: fills the remaining
    factor with data (or fsdp) parallelism."""
    devices = _default_devices(model_parallel * seq_parallel)
    n = len(devices)
    rest = n // (model_parallel * seq_parallel)
    if rest * model_parallel * seq_parallel != n:
        raise ValueError(
            "%d devices not divisible by model=%d x seq=%d"
            % (n, model_parallel, seq_parallel))
    cfg = MeshConfig(
        data=1 if fsdp else rest, fsdp=rest if fsdp else 1,
        model=model_parallel, seq=seq_parallel)
    return create_mesh(cfg, devices=devices)


def current_mesh():
    """The innermost active mesh (mesh_scope), or None."""
    stack = getattr(_STATE, "stack", None)
    if stack:
        return stack[-1]
    return None


@contextlib.contextmanager
def mesh_scope(mesh):
    """`with mesh_scope(mesh):` — sets both our thread-local current mesh and
    jax's global mesh context (so bare PartitionSpecs in shard_map resolve)."""
    stack = getattr(_STATE, "stack", None)
    if stack is None:
        stack = _STATE.stack = []
    stack.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        stack.pop()
