"""Sharded (pod-scale) checkpointing for functional param trees.

The reference checkpoints are single-host files (.params dmlc framing,
SURVEY.md §5.4 — implemented in io/params_serde.py for compatibility).
Those cannot hold a Llama-8B sharded across a v5e-64 mesh: each host must
write only its addressable shards and restore must re-lay arrays onto the
mesh. This module provides that native format over orbax (OCDBT), the
jax-ecosystem standard:

  save_sharded(path, tree, step)        — async-capable multi-host save
  restore_sharded(path, mesh, rules)    — restore with target shardings
  latest_step(path)

Checkpoint/resume policy matches the reference (§5.3): periodic epoch/step
saves + explicit resume; no elastic membership.
"""
from __future__ import annotations

import os

import jax
from jax.sharding import NamedSharding

from .sharding import ShardingRules

__all__ = ["save_sharded", "restore_sharded", "latest_step",
           "save_train_state", "restore_train_state"]


def _mgr(path):
    import orbax.checkpoint as ocp
    return ocp.CheckpointManager(os.path.abspath(path))


def save_sharded(path, tree, step=0, wait=True):
    """Write one step of a (possibly sharded) pytree. Every process must
    call this (multi-host collective); single-process works as-is."""
    import orbax.checkpoint as ocp
    mgr = _mgr(path)
    mgr.save(int(step), args=ocp.args.StandardSave(tree))
    if wait:
        mgr.wait_until_finished()
    mgr.close()


def latest_step(path):
    mgr = _mgr(path)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_sharded(path, step=None, mesh=None, rules=None, template=None):
    """Restore a step. With mesh+rules (or an explicit template tree of
    jax.ShapeDtypeStruct/arrays), arrays come back with the target
    NamedShardings — each host reads only its shards."""
    import orbax.checkpoint as ocp
    mgr = _mgr(path)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            mgr.close()
            raise FileNotFoundError("no checkpoint under %s" % path)
    if template is None and mesh is not None:
        meta = mgr.item_metadata(int(step))
        tree_meta = getattr(meta, "item_metadata", meta)
        rules = rules or ShardingRules([])
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree_meta)
        outs = []
        for keypath, leaf in flat:
            name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                            for k in keypath)
            spec = rules.spec_for(name, tuple(leaf.shape), mesh)
            outs.append(jax.ShapeDtypeStruct(
                tuple(leaf.shape), leaf.dtype,
                sharding=NamedSharding(mesh, spec)))
        template = jax.tree_util.tree_unflatten(treedef, outs)
    if template is not None:
        restored = mgr.restore(
            int(step), args=ocp.args.StandardRestore(template))
    else:
        restored = mgr.restore(int(step))
    mgr.close()
    return restored


def save_train_state(path, params, opt_state, step):
    """Params + optimizer state in one step dir (the Trainer.save_states
    analog for the fused ShardedTrainStep path)."""
    save_sharded(path, {"params": params, "opt_state": opt_state,
                        "step": int(step)}, step=step)


def restore_train_state(path, mesh=None, rules=None, step=None):
    tree = restore_sharded(path, step=step, mesh=mesh, rules=rules)
    return tree["params"], tree["opt_state"], tree["step"]
