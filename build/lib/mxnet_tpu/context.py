"""Device contexts.

TPU-native analog of the reference's `Context` (reference: include/mxnet/base.h
(Context), python/mxnet/context.py). Device types keep the reference's integer
codes and add kTPU; every Context resolves to a concrete `jax.Device`.

On this stack a "gpu" context is an alias for the accelerator (TPU) so that
reference scripts written as `mx.gpu(0)` run unchanged.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "cpu_shared",
           "num_gpus", "num_tpus", "current_context"]


class Context:
    """Device context. reference: include/mxnet/base.h (Context struct)."""

    # reference device-type codes (DEV_MASK values) + new kTPU
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        if not hasattr(Context._default_ctx, "value"):
            Context._default_ctx.value = Context("cpu", 0)
        self._old_ctx = Context._default_ctx.value
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # ---- jax resolution ------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device.

        cpu() prefers a real CPU backend; when the platform exposes only the
        accelerator (axon plugin disables CPU fallback) every context resolves
        to an accelerator device so reference scripts still run.
        """
        return _resolve_device(self.device_type, self.device_id)

    def empty_cache(self):
        """reference: Context::empty_cache / MXStorageEmptyCache. XLA's
        allocator pools buffers internally; live-buffer GC is automatic."""
        return None


def _accel_devices():
    # local (addressable) devices only: under the multi-controller runtime
    # each process owns its slice of the pod; committing data to another
    # process's device is invalid (reference analog: a worker only touches
    # its own GPUs)
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    return devs if devs else jax.local_devices()


def _cpu_devices():
    try:
        return jax.local_devices(backend="cpu")
    except RuntimeError:
        return []


def _resolve_device(device_type, device_id):
    if device_type in ("gpu", "tpu"):
        devs = _accel_devices()
        return devs[device_id % len(devs)]
    devs = _cpu_devices()
    if devs:
        return devs[device_id % len(devs)]
    return jax.local_devices()[0]


def cpu(device_id=0):
    """reference: python/mxnet/context.py (cpu)."""
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    """Pinned host memory. PjRt H2D transfers stage internally; alias of cpu."""
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id=0):
    """POSIX-shm storage for DataLoader workers in the reference; alias of cpu."""
    return Context("cpu_shared", device_id)


def gpu(device_id=0):
    """Accelerator context; on this stack an alias for the TPU so that
    reference `mx.gpu(i)` scripts run unchanged."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    """The native device context of this framework (north star: `mx.tpu()`)."""
    return Context("tpu", device_id)


def num_gpus():
    """reference: python/mxnet/context.py (num_gpus). Counts this process's
    accelerators (local, like the reference's cudaGetDeviceCount)."""
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    return len(devs)


def num_tpus():
    return num_gpus()


def current_context():
    """reference: python/mxnet/context.py (current_context) — thread-local
    `with ctx:` stack, default cpu(0)."""
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value
