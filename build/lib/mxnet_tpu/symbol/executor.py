"""Executor: a bound symbolic graph.

TPU-native analog of reference src/executor/graph_executor.cc via
python/mxnet/executor.py. `forward` evaluates the graph through NDArray ops
under autograd (recording when is_train), `backward` replays the tape into
the bound grad arrays. Memory planning / op fusion (PlanMemory, bulk exec)
are XLA's job; a jitted fast path is available via `hybridize`-style caching
in CachedOp, which Module uses for its hot loop.
"""
from __future__ import annotations

import numpy as _np

from .. import autograd
from .. import ndarray as nd
from ..base import MXNetError

__all__ = ["Executor"]


class Executor:
    """reference: python/mxnet/executor.py (Executor)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None):
        self._symbol = symbol
        self._ctx = ctx
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()

        if isinstance(args, (list, tuple)):
            if len(args) != len(self._arg_names):
                raise MXNetError("bind: expected %d args, got %d" %
                                 (len(self._arg_names), len(args)))
            self.arg_dict = dict(zip(self._arg_names, args))
        else:
            self.arg_dict = dict(args)
        self.arg_arrays = [self.arg_dict[n] for n in self._arg_names]

        if args_grad is None:
            self.grad_dict = {}
        elif isinstance(args_grad, (list, tuple)):
            self.grad_dict = dict(zip(self._arg_names, args_grad))
        else:
            self.grad_dict = dict(args_grad)
        self.grad_arrays = [self.grad_dict.get(n) for n in self._arg_names]

        if aux_states is None:
            self.aux_dict = {}
        elif isinstance(aux_states, (list, tuple)):
            self.aux_dict = dict(zip(self._aux_names, aux_states))
        else:
            self.aux_dict = dict(aux_states)
        self.aux_arrays = [self.aux_dict[n] for n in self._aux_names]

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._arg_names, grad_req))
        else:
            self._grad_req = dict(grad_req)

        self.outputs = []
        self._output_names = symbol.list_outputs()
        self._recorded_heads = None

    def forward(self, is_train=False, **kwargs):
        """reference: Executor.forward — kwargs update bound args first."""
        for name, val in kwargs.items():
            if name not in self.arg_dict:
                raise MXNetError("Unknown argument %s" % name)
            dst = self.arg_dict[name]
            if isinstance(val, nd.NDArray):
                val.copyto(dst)
            else:
                dst[:] = val

        feed = dict(self.arg_dict)
        feed.update(self.aux_dict)
        if is_train:
            # mark grads on inputs that want them
            for name, arr in self.arg_dict.items():
                req = self._grad_req.get(name, "null")
                if req != "null" and self.grad_dict.get(name) is not None:
                    arr._grad = self.grad_dict[name]
                    arr._grad_req = req
                    autograd.mark_variable(arr, req)
            with autograd.record():
                out = self._symbol.eval_with(feed, self._ctx)
        else:
            with autograd.pause():
                out = self._symbol.eval_with(feed, self._ctx)
        self.outputs = out if isinstance(out, list) else [out]
        self._recorded_heads = self.outputs if is_train else None
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """reference: Executor.backward."""
        if self._recorded_heads is None:
            raise MXNetError("backward called before forward(is_train=True)")
        if out_grads is None:
            head_grads = None
        else:
            if isinstance(out_grads, nd.NDArray):
                out_grads = [out_grads]
            head_grads = list(out_grads)
        autograd.backward(self._recorded_heads, head_grads)
        return

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """reference: Executor.copy_params_from."""
        for name, array in arg_params.items():
            if name in self.arg_dict:
                array.copyto(self.arg_dict[name]) if isinstance(
                    array, nd.NDArray) else self.arg_dict[name].__setitem__(
                        slice(None), array)
            elif not allow_extra_params:
                raise ValueError("Find name \"%s\" that is not in the "
                                 "arguments" % name)
        if aux_params is None:
            return
        for name, array in aux_params.items():
            if name in self.aux_dict:
                array.copyto(self.aux_dict[name])
            elif not allow_extra_params:
                raise ValueError("Find name %s that is not in the auxiliary "
                                 "states" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new shapes. reference: Executor.reshape."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for name, sh in zip(self._arg_names, arg_shapes):
            old = self.arg_dict[name]
            if tuple(old.shape) == tuple(sh):
                new_args[name] = old
            else:
                new_args[name] = nd.zeros(sh, ctx=self._ctx, dtype=old.dtype)
        new_grads = None
        if self.grad_dict:
            new_grads = {}
            for name, g in self.grad_dict.items():
                if g is None:
                    continue
                sh = new_args[name].shape
                new_grads[name] = g if tuple(g.shape) == tuple(sh) else \
                    nd.zeros(sh, ctx=self._ctx, dtype=g.dtype)
        new_aux = {}
        for name, sh in zip(self._aux_names, aux_shapes):
            old = self.aux_dict[name]
            new_aux[name] = old if tuple(old.shape) == tuple(sh) else \
                nd.zeros(sh, ctx=self._ctx, dtype=old.dtype)
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self._grad_req, new_aux)

    @property
    def output_dict(self):
        return dict(zip(self._output_names, self.outputs))
