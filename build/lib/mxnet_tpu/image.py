"""Image IO + augmentation. reference: python/mxnet/image/image.py.

The reference decodes via OpenCV inside libmxnet (`mx.image.imdecode` →
cv::imdecode); here decoding uses PIL (baked into this environment) or raw
.npy payloads (written by this build's pack_img), and resize runs through
jax.image on device when given an NDArray. Augmenter classes and
CreateAugmenter mirror the reference.
"""
from __future__ import annotations

import io
import os
import random

import numpy as _np

from . import ndarray as nd
from .base import MXNetError

__all__ = ["imdecode", "imread", "imresize", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "Augmenter", "SequentialAug", "RandomOrderAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "HorizontalFlipAug",
           "CastAug", "ColorNormalizeAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "RandomGrayAug",
           "CreateAugmenter", "ImageIter"]


def imdecode(buf, flag=1, to_rgb=True, to_ndarray=True):
    """Decode an image byte buffer (JPEG/PNG via PIL, .npy via numpy).
    reference: image.py (imdecode) → cv::imdecode."""
    if isinstance(buf, nd.NDArray):
        buf = buf.asnumpy().tobytes()
    if isinstance(buf, (bytearray, memoryview)):
        buf = bytes(buf)
    if buf[:6] == b"\x93NUMPY":
        arr = _np.load(io.BytesIO(buf), allow_pickle=False)
    else:
        from PIL import Image
        img = Image.open(io.BytesIO(buf))
        if flag == 0:
            img = img.convert("L")
        elif img.mode != "RGB":
            img = img.convert("RGB")
        arr = _np.asarray(img)
        if not to_rgb and arr.ndim == 3:
            arr = arr[:, :, ::-1]  # BGR like OpenCV default
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if to_ndarray:
        return nd.array(arr, dtype="uint8")
    return arr


def imread(filename, flag=1, to_rgb=True):
    """reference: image.py (imread)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=1):
    """Resize to (h, w). reference: image.py (imresize) → cv::resize;
    here jax.image.resize (device-side)."""
    import jax
    import jax.numpy as jnp
    method = {0: "nearest", 1: "bilinear", 2: "cubic", 3: "bilinear",
              4: "bilinear"}.get(interp, "bilinear")
    raw = src.data_jax if isinstance(src, nd.NDArray) else jnp.asarray(
        _np.asarray(src))
    out_shape = (h, w) + tuple(raw.shape[2:])
    out = jax.image.resize(raw.astype(jnp.float32), out_shape, method=method)
    if raw.dtype == jnp.uint8:
        out = jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)
    else:
        out = out.astype(raw.dtype)
    return nd.from_jax(out)


def resize_short(src, size, interp=2):
    """Resize shorter edge to `size`. reference: image.py (resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """reference: image.py (fixed_crop)."""
    out = src[y0:y0 + h, x0:x0 + w]
    if isinstance(out, nd.NDArray) and out._base is not None:
        out = nd.from_jax(out._read())
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp=interp)
    return out


def random_crop(src, size, interp=2):
    """reference: image.py (random_crop)."""
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """reference: image.py (center_crop)."""
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """reference: image.py (color_normalize)."""
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


def random_size_crop(src, size, area, ratio, interp=2):
    """reference: image.py (random_size_crop)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (float, int)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = random.uniform(area[0], area[1]) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        new_ratio = _np.exp(random.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * new_ratio)))
        new_h = int(round(_np.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


class Augmenter:
    """Base augmenter. reference: image.py (Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if isinstance(v, nd.NDArray):
                kwargs[k] = v.asnumpy().tolist()
            elif isinstance(v, _np.ndarray):
                kwargs[k] = v.tolist()

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    """reference: image.py (SequentialAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    """reference: image.py (RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        random.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    """reference: image.py (ResizeAug)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    """reference: image.py (ForceResizeAug)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    """reference: image.py (RandomCropAug)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    """reference: image.py (RandomSizedCropAug)."""

    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    """reference: image.py (CenterCropAug)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    """reference: image.py (HorizontalFlipAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            return nd.invoke("reverse", src, axis=1)
        return src


class CastAug(Augmenter):
    """reference: image.py (CastAug)."""

    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    """reference: image.py (ColorNormalizeAug)."""

    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = nd.array(mean) if mean is not None and not isinstance(
            mean, nd.NDArray) else mean
        self.std = nd.array(std) if std is not None and not isinstance(
            std, nd.NDArray) else std

    def __call__(self, src):
        return color_normalize(src.astype("float32"), self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    """reference: image.py (BrightnessJitterAug)."""

    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.brightness, self.brightness)
        return src.astype("float32") * alpha


class ContrastJitterAug(Augmenter):
    """reference: image.py (ContrastJitterAug)."""

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = _np.array([[[0.299, 0.587, 0.114]]], dtype="float32")

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.contrast, self.contrast)
        src = src.astype("float32")
        gray = (src * nd.array(self.coef)).sum()
        gray = (3.0 * (1.0 - alpha) / src.size) * gray
        return src * alpha + gray


class SaturationJitterAug(Augmenter):
    """reference: image.py (SaturationJitterAug)."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = _np.array([[[0.299, 0.587, 0.114]]], dtype="float32")

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.saturation, self.saturation)
        src = src.astype("float32")
        gray = (src * nd.array(self.coef)).sum(axis=2, keepdims=True)
        gray = gray * (1.0 - alpha)
        return src * alpha + gray


class HueJitterAug(Augmenter):
    """reference: image.py (HueJitterAug) — YIQ rotation approximation."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = _np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], dtype="float32")
        self.ityiq = _np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], dtype="float32")

    def __call__(self, src):
        alpha = random.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                       dtype="float32")
        t = _np.dot(_np.dot(self.ityiq, bt), self.tyiq).T
        return nd.invoke("dot", src.astype("float32"), nd.array(t))


class ColorJitterAug(RandomOrderAug):
    """reference: image.py (ColorJitterAug)."""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA lighting jitter. reference: image.py (LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd, eigval=eigval, eigvec=eigvec)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval)
        self.eigvec = _np.asarray(eigvec)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = _np.dot(self.eigvec * alpha, self.eigval)
        return src.astype("float32") + nd.array(rgb)


class RandomGrayAug(Augmenter):
    """reference: image.py (RandomGrayAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = _np.array([[0.21, 0.21, 0.21],
                              [0.72, 0.72, 0.72],
                              [0.07, 0.07, 0.07]], dtype="float32")

    def __call__(self, src):
        if random.random() < self.p:
            src = nd.invoke("dot", src.astype("float32"), nd.array(self.mat))
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmentation pipeline.
    reference: image.py (CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0,
                                                            4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = _np.asarray(mean)
        assert mean.shape[0] in [1, 3]
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = _np.asarray(std)
        assert std.shape[0] in [1, 3]
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Image iterator over .rec files or .lst + image dir, with augmenters.
    reference: python/mxnet/image/image.py (ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 part_index=0, num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 last_batch_handle="pad", shuffle=False, **kwargs):
        from .io.io import DataDesc
        assert path_imgrec or path_imglist or imglist is not None
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.path_root = path_root
        self.shuffle = shuffle
        self._allow_read = True

        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            from .recordio import MXIndexedRecordIO, MXRecordIO
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.isfile(idx_path):
                self.imgrec = MXIndexedRecordIO(idx_path, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = MXRecordIO(path_imgrec, "r")
        elif path_imglist:
            with open(path_imglist) as fin:
                imglist = {}
                imgkeys = []
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    label = _np.array(line[1:-1], dtype="float32")
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
                self.imglist = imglist
                self.seq = imgkeys
        elif imglist is not None:
            result = {}
            imgkeys = []
            index = 1
            for img in imglist:
                key = str(index)
                index += 1
                label = _np.array(img[0], dtype="float32") if not isinstance(
                    img[0], (int, float)) else _np.array([img[0]],
                                                         dtype="float32")
                result[key] = (label, img[1])
                imgkeys.append(str(key))
            self.imglist = result
            self.seq = imgkeys
        if num_parts > 1 and self.seq is not None:
            assert part_index < num_parts
            N = len(self.seq)
            C = N // num_parts
            self.seq = self.seq[part_index * C:(part_index + 1) * C]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self.dtype = dtype
        self.data_name = data_name
        self.label_name = label_name
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + self.data_shape)]
        if label_width > 1:
            self.provide_label = [DataDesc(label_name,
                                           (batch_size, label_width))]
        else:
            self.provide_label = [DataDesc(label_name, (batch_size,))]
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            random.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self, decode=True):
        """Returns (label, decoded image); decode=False returns the raw
        payload (record bytes / file name) so construction-time label
        scans need not pay the image decode."""
        from .recordio import unpack
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = unpack(s)
                return header.label, (imdecode(img) if decode else img)
            label, fname = self.imglist[idx]
            if not decode:
                return label, fname
            return label, imread(os.path.join(self.path_root, fname))
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = unpack(s)
        return header.label, (imdecode(img) if decode else img)

    def next(self):
        """Returns the next DataBatch."""
        from .io.io import DataBatch
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = _np.zeros((batch_size, h, w, c), dtype="float32")
        batch_label = _np.zeros((batch_size, self.label_width),
                                dtype="float32")
        i = 0
        pad = 0
        try:
            while i < batch_size:
                label, data = self.next_sample()
                data = self.augmentation_transform(data)
                batch_data[i] = data.asnumpy() if isinstance(
                    data, nd.NDArray) else data
                batch_label[i] = label
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = batch_size - i
            for j in range(i, batch_size):
                batch_data[j] = batch_data[j % max(i, 1)]
                batch_label[j] = batch_label[j % max(i, 1)]
        data_nchw = _np.transpose(batch_data, (0, 3, 1, 2))
        label_out = batch_label[:, 0] if self.label_width == 1 else \
            batch_label
        return DataBatch([nd.array(data_nchw, dtype=self.dtype)],
                         [nd.array(label_out)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self

    def augmentation_transform(self, data):
        for aug in self.auglist:
            data = aug(data)
        return data

# Detection iterator + label-aware augmenters (reference: image/detection.py)
from .image_detection import (DetAugmenter, DetBorrowAug,   # noqa: E402,F401
                              DetRandomSelectAug, DetHorizontalFlipAug,
                              DetRandomCropAug, DetRandomPadAug,
                              CreateDetAugmenter, ImageDetIter)
__all__ += ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
            "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
            "CreateDetAugmenter", "ImageDetIter"]
