"""`mx.np.random`. reference: python/mxnet/numpy/random.py — numpy-named
sampling backed by the framework RNG (mx.random.seed applies). Derived
distributions (lognormal/laplace/gumbel/weibull/...) are inverse-CDF or
composition transforms of the registered uniform/normal/gamma ops — the
same construction the reference's src/operator/numpy/random/*.cc kernels
use — so every draw consumes the per-device key table and is reproducible
under mx.random.seed."""
from __future__ import annotations

import numpy as _onp

from ..ndarray.ndarray import invoke as _raw_invoke, NDArray
from .. import random as _random
from .multiarray import as_np_ndarray as _as_np


def invoke(*args, **kwargs):
    return _as_np(_raw_invoke(*args, **kwargs))

__all__ = ["seed", "uniform", "normal", "randn", "rand", "randint",
           "choice", "shuffle", "permutation", "gamma", "beta",
           "exponential", "multinomial", "lognormal", "laplace",
           "logistic", "gumbel", "pareto", "power", "rayleigh", "weibull",
           "chisquare", "f", "poisson", "standard_normal",
           "standard_exponential", "standard_gamma", "standard_cauchy",
           "multivariate_normal", "bernoulli", "binomial",
           "negative_binomial"]

seed = _random.seed


def uniform(low=0.0, high=1.0, size=None, dtype=None, ctx=None):
    return invoke("_random_uniform", low=float(low), high=float(high),
                  shape=size if size is not None else (), ctx=ctx,
                  dtype=dtype or "float32")


def normal(loc=0.0, scale=1.0, size=None, dtype=None, ctx=None):
    return invoke("_random_normal", loc=float(loc), scale=float(scale),
                  shape=size if size is not None else (), ctx=ctx,
                  dtype=dtype or "float32")


def randn(*size, **kwargs):
    return normal(size=size or (), **kwargs)


def rand(*size, **kwargs):
    return uniform(size=size or (), **kwargs)


def randint(low, high=None, size=None, dtype=None, ctx=None):
    if high is None:
        low, high = 0, low
    return invoke("_random_randint", low=int(low), high=int(high),
                  shape=size if size is not None else (), ctx=ctx,
                  dtype=dtype or "int32")


def exponential(scale=1.0, size=None, ctx=None):
    return invoke("_random_exponential", lam=1.0 / scale,
                  shape=size if size is not None else (), ctx=ctx)


def gamma(shape, scale=1.0, size=None, ctx=None):
    return invoke("_random_gamma", alpha=float(shape), beta=float(scale),
                  shape=size if size is not None else (), ctx=ctx)


def beta(a, b, size=None, ctx=None):
    # beta(a,b) = ga/(ga+gb) from two gammas (reference implements the same
    # composition for its numpy namespace)
    ga = gamma(a, 1.0, size=size, ctx=ctx)
    gb = gamma(b, 1.0, size=size, ctx=ctx)
    return ga / (ga + gb)


def poisson(lam=1.0, size=None, ctx=None):
    return invoke("_random_poisson", lam=float(lam),
                  shape=size if size is not None else (), ctx=ctx)


def negative_binomial(n, p, size=None, ctx=None):
    return invoke("_random_negative_binomial", k=int(n), p=float(p),
                  shape=size if size is not None else (), ctx=ctx)


# -- derived transforms (each consumes framework-RNG uniforms/normals) ----
def standard_normal(size=None, ctx=None):
    return normal(0.0, 1.0, size=size, ctx=ctx)


def standard_exponential(size=None, ctx=None):
    return exponential(1.0, size=size, ctx=ctx)


def standard_gamma(shape, size=None, ctx=None):
    return gamma(shape, 1.0, size=size, ctx=ctx)


def standard_cauchy(size=None, ctx=None):
    from . import tan, pi
    u = _clip_open(uniform(0.0, 1.0, size=size, ctx=ctx))
    return tan(pi * (u - 0.5))


def lognormal(mean=0.0, sigma=1.0, size=None, ctx=None):
    from . import exp
    return exp(normal(mean, sigma, size=size, ctx=ctx))


def laplace(loc=0.0, scale=1.0, size=None, ctx=None):
    from . import sign, log1p, abs as _abs, clip
    # keep |u| strictly below 0.5: a draw of exactly -0.5 would hit
    # log1p(-1) = -inf
    u = clip(uniform(-0.5, 0.5, size=size, ctx=ctx), -0.5 + 1e-7,
             0.5 - 1e-7)
    return loc - scale * sign(u) * log1p(-2.0 * _abs(u))


def logistic(loc=0.0, scale=1.0, size=None, ctx=None):
    from . import log
    u = _clip_open(uniform(0.0, 1.0, size=size, ctx=ctx))
    return loc + scale * log(u / (1.0 - u))


def gumbel(loc=0.0, scale=1.0, size=None, ctx=None):
    from . import log
    u = _clip_open(uniform(0.0, 1.0, size=size, ctx=ctx))
    return loc - scale * log(-log(u))


def pareto(a, size=None, ctx=None):
    # numpy draws from the Lomax (Pareto II): (1-u)^{-1/a} - 1
    from . import power as _pow
    u = _clip_open(uniform(0.0, 1.0, size=size, ctx=ctx))
    return _pow(1.0 - u, -1.0 / float(a)) - 1.0


def power(a, size=None, ctx=None):
    from . import power as _pow
    u = _clip_open(uniform(0.0, 1.0, size=size, ctx=ctx))
    return _pow(u, 1.0 / float(a))


def rayleigh(scale=1.0, size=None, ctx=None):
    from . import sqrt, log
    u = _clip_open(uniform(0.0, 1.0, size=size, ctx=ctx))
    return scale * sqrt(-2.0 * log(u))


def weibull(a, size=None, ctx=None):
    from . import power as _pow, log
    u = _clip_open(uniform(0.0, 1.0, size=size, ctx=ctx))
    return _pow(-log(u), 1.0 / float(a))


def chisquare(df, size=None, ctx=None):
    return gamma(df / 2.0, 2.0, size=size, ctx=ctx)


def f(dfnum, dfden, size=None, ctx=None):
    num = chisquare(dfnum, size=size, ctx=ctx) / float(dfnum)
    den = chisquare(dfden, size=size, ctx=ctx) / float(dfden)
    return num / den


def bernoulli(prob=0.5, size=None, ctx=None):
    u = uniform(0.0, 1.0, size=size, ctx=ctx)
    return (u < prob).astype("float32")


def binomial(n, p, size=None, ctx=None):
    """Sum of n bernoulli draws — one (…, n) uniform draw and one
    reduction, not n sequential dispatches."""
    from . import zeros
    shape = tuple(size) if size is not None and not _onp.isscalar(size) \
        else ((int(size),) if size is not None else ())
    if int(n) == 0:
        return zeros(shape, ctx=ctx)
    u = uniform(0.0, 1.0, size=shape + (int(n),), ctx=ctx)
    return (u < p).astype("float32").sum(axis=-1)


def _clip_open(u, eps=1e-7):
    """Keep uniforms strictly inside (0,1) so log/pow transforms stay
    finite."""
    from . import clip
    return clip(u, eps, 1.0 - eps)


def multivariate_normal(mean, cov, size=None, ctx=None):
    from . import array as _np_array
    from .linalg import cholesky
    mean = mean if isinstance(mean, NDArray) else _np_array(mean)
    cov = cov if isinstance(cov, NDArray) else _np_array(cov)
    d = mean.shape[-1]
    count = (size,) if isinstance(size, int) else (size or ())
    z = normal(0.0, 1.0, size=tuple(count) + (d,), ctx=ctx)
    L = cholesky(cov)
    return mean + z @ L.T


def _rand_perm_idx(n, ctx=None):
    """Random permutation of [0, n) via argsort of framework uniforms —
    every draw consumes the per-device key table, so mx.random.seed
    reproduces it (host numpy RNG would not)."""
    from . import argsort
    u = uniform(0.0, 1.0, size=(int(n),), ctx=ctx)
    return argsort(u)


def choice(a, size=None, replace=True, p=None, ctx=None):
    from ..ndarray.ndarray import array as nd_array
    from . import argsort, cumsum, searchsorted, log, array as _np_array
    n = int(a) if _onp.isscalar(a) else len(a)
    count = int(_onp.prod(size)) if size else 1
    if p is None:
        if replace:
            idx = randint(0, n, size=size, ctx=ctx)
        else:
            idx = _rand_perm_idx(n, ctx)[:count].reshape(size or ())
    else:
        pv = _np_array(_onp.asarray(p, dtype=_onp.float32))
        if replace:
            # inverse-CDF draw (reference: SampleMultinomial kernel)
            cdf = cumsum(pv)
            u = uniform(0.0, 1.0, size=(count,), ctx=ctx) * cdf[-1]
            idx = searchsorted(cdf, u, side="right").reshape(size or ())
        else:
            # Gumbel-top-k: weighted sampling without replacement
            z = log(_clip_open(pv, 1e-12)) + gumbel(0.0, 1.0,
                                                    size=(n,), ctx=ctx)
            idx = argsort(-z)[:count].reshape(size or ())
    if _onp.isscalar(a):
        return _as_np(idx.astype("int64"))
    return _as_np(nd_array(_onp.asarray(a))[idx.astype("int32")])


def multinomial(n, pvals, size=None):
    """Counts of n inverse-CDF draws per experiment — one vectorized
    (experiments, n) draw, framework RNG so seeded runs reproduce
    (reference: _sample_multinomial)."""
    from . import (array as _np_array, cumsum, searchsorted, arange,
                   expand_dims)
    pv = _np_array(_onp.asarray(pvals, dtype=_onp.float32))
    k = pv.shape[0]
    cdf = cumsum(pv)
    experiments = int(_onp.prod(size)) if size else 1
    u = uniform(0.0, 1.0, size=(experiments, int(n))) * cdf[-1]
    idx = searchsorted(cdf, u, side="right")          # (experiments, n)
    counts = (expand_dims(idx, -1) ==
              arange(k, dtype="int32")).astype("float32").sum(axis=1)
    if size is None:
        return _as_np(counts[0])
    if not _onp.isscalar(size):
        counts = counts.reshape(tuple(size) + (k,))
    return _as_np(counts)


def shuffle(x):
    """In-place permutation along axis 0 (reference: np.random.shuffle),
    drawn from the framework RNG (mx.random.seed applies)."""
    x[:] = x[_rand_perm_idx(x.shape[0],
                            getattr(x, "context", None)).astype("int32")]


def permutation(x, ctx=None):
    from . import array as _np_array, arange
    if _onp.isscalar(x):
        return _as_np(_rand_perm_idx(int(x), ctx))
    out = (x if isinstance(x, NDArray) else _np_array(x)).copy()
    shuffle(out)
    return _as_np(out)
