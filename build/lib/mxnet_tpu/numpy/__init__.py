"""`mx.np` — NumPy-compatible array namespace.

reference: python/mxnet/numpy/ (mx.np) + numpy_extension (mx.npx): a
numpy-semantics array API (zero-dim arrays, numpy broadcasting/naming)
running on the framework engine. The reference's `multiarray.py` is ~20K
LoC of per-function ctypes veneers over `_npi_*` C ops; here every function
is registered once as an op (`_np_<name>`) wrapping the jax.numpy
implementation and dispatched through the standard imperative `invoke`, so
autograd recording, the profiler, AMP casts, and the NaiveEngine sync mode
all apply exactly as for `mx.nd` ops — and `hybridize()` can trace through
them.

Surface organization (mirrors the reference's groups in
python/mxnet/numpy/multiarray.py and numpy/function_base.py):
  - dispatched ops: one `_np_*` registry entry per jnp callable
  - creation: host-builds the value, wraps on the current Context
  - mutating (fill_diagonal/place/put/copyto/...): functional jnp result
    buffer-swapped into the target NDArray (engine-safe mutation)
  - host-side metadata (result_type/can_cast/finfo/...): no dispatch
  - dtypes/constants: numpy's own scalars (jnp consumes them 1:1)
"""
from __future__ import annotations

import builtins as _builtins

import numpy as _onp

import jax.numpy as jnp

from ..ops import registry as _reg
from ..ndarray.ndarray import NDArray, invoke, array as _nd_array, from_jax
from ..context import current_context
from .multiarray import ndarray, as_np_ndarray

# ---------------------------------------------------------------------------
# (name, differentiable) — jnp callables surfaced 1:1 through the registry.
# Integer/boolean/index producers are non-differentiable (the reference marks
# the matching `_npi_*` ops FGradient-less the same way).
# ---------------------------------------------------------------------------
_FUNCS = [
    # -- elementwise arithmetic ------------------------------------------
    ("add", True), ("subtract", True), ("multiply", True), ("divide", True),
    ("true_divide", True), ("mod", True), ("remainder", True), ("fmod", True),
    ("power", True), ("pow", True), ("float_power", True),
    ("maximum", True), ("minimum", True), ("fmax", True), ("fmin", True),
    ("hypot", True), ("negative", True), ("positive", True),
    ("reciprocal", True), ("abs", True), ("absolute", True), ("fabs", True),
    ("sign", True), ("heaviside", True), ("copysign", True), ("ldexp", True),
    ("nextafter", False), ("spacing", False), ("signbit", False),
    # -- exp/log/trig ----------------------------------------------------
    ("exp", True), ("exp2", True), ("expm1", True), ("log", True),
    ("log2", True), ("log10", True), ("log1p", True),
    ("logaddexp", True), ("logaddexp2", True),
    ("sqrt", True), ("cbrt", True), ("square", True),
    ("sin", True), ("cos", True), ("tan", True),
    ("arcsin", True), ("arccos", True), ("arctan", True), ("arctan2", True),
    ("asin", True), ("acos", True), ("atan", True), ("atan2", True),
    ("sinh", True), ("cosh", True), ("tanh", True),
    ("arcsinh", True), ("arccosh", True), ("arctanh", True),
    ("asinh", True), ("acosh", True), ("atanh", True),
    ("sinc", True), ("i0", True), ("angle", True), ("unwrap", True),
    ("degrees", True), ("radians", True), ("deg2rad", True),
    ("rad2deg", True),
    # -- rounding --------------------------------------------------------
    ("rint", True), ("floor", True), ("ceil", True), ("trunc", True),
    ("round", True), ("around", True), ("clip", True), ("nan_to_num", True),
    # -- linear algebra / products ---------------------------------------
    ("dot", True), ("matmul", True), ("inner", True), ("outer", True),
    ("tensordot", True), ("einsum", True), ("vdot", True), ("vecdot", True),
    ("kron", True), ("cross", True), ("trace", True),
    ("matrix_transpose", True),
    # -- reductions ------------------------------------------------------
    ("sum", True), ("prod", True), ("mean", True), ("std", True),
    ("var", True), ("cumsum", True), ("cumprod", True),
    ("max", True), ("min", True), ("amax", True), ("amin", True),
    ("ptp", True), ("median", True), ("quantile", True),
    ("percentile", True), ("average", True),
    ("nansum", True), ("nanprod", True), ("nanmean", True),
    ("nanstd", True), ("nanvar", True), ("nanmedian", True),
    ("nanquantile", True), ("nanpercentile", True),
    ("nanmax", True), ("nanmin", True),
    ("nancumsum", True), ("nancumprod", True),
    ("nanargmax", False), ("nanargmin", False),
    ("trapezoid", True), ("corrcoef", True), ("cov", True),
    # -- shape manipulation ----------------------------------------------
    ("reshape", True), ("ravel", True), ("transpose", True),
    ("permute_dims", True), ("swapaxes", True), ("moveaxis", True),
    ("rollaxis", True), ("expand_dims", True), ("squeeze", True),
    ("broadcast_to", True), ("concatenate", True), ("concat", True),
    ("stack", True), ("vstack", True), ("hstack", True), ("dstack", True),
    ("column_stack", True), ("split", True), ("array_split", True),
    ("vsplit", True), ("hsplit", True), ("dsplit", True),
    ("tile", True), ("repeat", True), ("roll", True), ("flip", True),
    ("fliplr", True), ("flipud", True), ("rot90", True), ("pad", True),
    ("append", True), ("delete", True), ("insert", True), ("resize", True),
    ("trim_zeros", True), ("broadcast_arrays", True), ("atleast_1d", True),
    ("atleast_2d", True), ("atleast_3d", True), ("astype", True),
    ("copy", True),
    # -- indexing / selection --------------------------------------------
    ("take", True), ("take_along_axis", True), ("where", True),
    ("select", True), ("compress", True), ("choose", True),
    ("extract", False), ("diag", True), ("diagflat", True),
    ("diagonal", True), ("tril", True), ("triu", True),
    ("meshgrid", True), ("ix_", False),
    # -- sorting / searching ---------------------------------------------
    ("sort", True), ("partition", True), ("argpartition", False),
    ("argmax", False), ("argmin", False), ("argsort", False),
    ("argwhere", False), ("searchsorted", False), ("flatnonzero", False),
    ("count_nonzero", False), ("nonzero", False), ("lexsort", False),
    ("sort_complex", False), ("digitize", False),
    # -- logic / comparison ----------------------------------------------
    ("floor_divide", False), ("equal", False), ("not_equal", False),
    ("greater", False), ("greater_equal", False), ("less", False),
    ("less_equal", False), ("logical_and", False), ("logical_or", False),
    ("logical_not", False), ("logical_xor", False),
    ("isnan", False), ("isinf", False), ("isfinite", False),
    ("isposinf", False), ("isneginf", False), ("isreal", False),
    ("iscomplex", False), ("all", False), ("any", False),
    ("allclose", False), ("isclose", False), ("array_equal", False),
    ("array_equiv", False), ("isin", False),
    # -- sets ------------------------------------------------------------
    ("unique", False), ("union1d", False), ("intersect1d", False),
    ("setdiff1d", False), ("setxor1d", False),
    ("unique_all", False), ("unique_counts", False),
    ("unique_inverse", False), ("unique_values", False),
    # -- integer / bit ops -----------------------------------------------
    ("lcm", False), ("gcd", False), ("bincount", False),
    ("bitwise_and", False), ("bitwise_or", False), ("bitwise_xor", False),
    ("bitwise_not", False), ("bitwise_invert", False),
    ("bitwise_count", False), ("invert", False),
    ("left_shift", False), ("right_shift", False),
    ("bitwise_left_shift", False), ("bitwise_right_shift", False),
    ("packbits", False), ("unpackbits", False),
    # -- misc numerics ---------------------------------------------------
    ("interp", True), ("diff", True), ("ediff1d", True), ("gradient", True),
    ("convolve", True), ("correlate", True), ("real", True), ("imag", True),
    ("conj", True), ("conjugate", True), ("histogram", False),
    ("histogram2d", False), ("histogramdd", False),
    ("histogram_bin_edges", False),
    # -- multi-output numerics -------------------------------------------
    ("frexp", False), ("modf", True), ("divmod", False),
    ("unravel_index", False), ("ravel_multi_index", False),
    # -- polynomials -----------------------------------------------------
    ("polyval", True), ("polyadd", True), ("polysub", True),
    ("polymul", True), ("polyder", True), ("polyint", True),
    ("polydiv", True), ("polyfit", True), ("poly", False), ("roots", False),
    ("vander", True),
    # -- functional ------------------------------------------------------
    ("apply_along_axis", False), ("apply_over_axes", False),
    ("piecewise", False),
]

# functions whose first argument is a sequence of arrays: the sequence is
# unpacked into positional args so the autograd tape records every input
_SEQ_FUNCS = {"concatenate", "concat", "stack", "vstack", "hstack",
              "dstack", "column_stack", "lexsort"}
# `fix` rounds toward zero == trunc; registered with an explicit impl
# because jnp.fix is deprecated (removal in jax 0.10) and jax warns on
# attribute access.
if "_np_fix" not in _reg.list_ops():
    _reg.register("_np_fix", differentiable=True)(
        lambda x: jnp.trunc(x))

_here = globals()


def _make(op_name, public_name, seq):
    def _fn(*args, **kwargs):
        if seq and len(args) >= 1 and isinstance(args[0], (list, tuple)):
            if len(args) > 1:
                # numpy allows axis positionally: concatenate((a, b), 1)
                kwargs.setdefault("axis", args[1])
            out = invoke(op_name, *args[0], **kwargs)
        else:
            out = invoke(op_name, *args, **kwargs)
        if out is kwargs.get("out"):
            return out  # caller-owned destination: don't retag it
        return as_np_ndarray(out)
    _fn.__name__ = public_name
    _fn.__qualname__ = public_name
    _fn.__doc__ = "numpy-compatible %s (jax.numpy.%s under invoke)" % (
        public_name, public_name)
    return _fn


for _name, _diff in _FUNCS:
    _jfn = getattr(jnp, _name, None)
    if _jfn is None:
        continue
    _op_name = "_np_" + _name
    if _op_name not in _reg.list_ops():
        if _name in _SEQ_FUNCS:
            def _seq_impl(*arrays, _jfn=_jfn, **kwargs):
                return _jfn(list(arrays), **kwargs)
            _reg.register(_op_name, differentiable=_diff)(_seq_impl)
        else:
            # normalize namedtuple results (unique_all, frexp via xla, ...)
            # to plain tuples: the tape hands plain-tuple cotangents to
            # jax.vjp, which rejects a pytree-structure mismatch
            def _impl(*args, _jfn=_jfn, **kwargs):
                out = _jfn(*args, **kwargs)
                if isinstance(out, tuple):
                    return tuple(out)
                if isinstance(out, list):
                    return tuple(out)
                return out
            _reg.register(_op_name, differentiable=_diff)(_impl)

_here["fix"] = _make("_np_fix", "fix", False)
for _name, _diff in _FUNCS:
    if getattr(jnp, _name, None) is None:
        continue
    _here[_name] = _make("_np_" + _name, _name, _name in _SEQ_FUNCS)


# ---------------------------------------------------------------------------
# creation & constants
# ---------------------------------------------------------------------------
pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None

# dtype aliases (reference: mx.np exposes numpy's scalar types verbatim)
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
half = _onp.half
single = _onp.single
double = _onp.double
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
intc = _onp.intc
intp = _onp.intp
int_ = _onp.int_
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
uint = _onp.uint
byte = _onp.byte
ubyte = _onp.ubyte
short = _onp.short
ushort = _onp.ushort
longlong = _onp.longlong
ulonglong = _onp.ulonglong
complex64 = _onp.complex64
complex128 = _onp.complex128
csingle = _onp.csingle
cdouble = _onp.cdouble
bool_ = _onp.bool_
float_ = _onp.float64
generic = _onp.generic
number = _onp.number
integer = _onp.integer
signedinteger = _onp.signedinteger
unsignedinteger = _onp.unsignedinteger
inexact = _onp.inexact
floating = _onp.floating
complexfloating = _onp.complexfloating
dtype = _onp.dtype
bfloat16 = jnp.bfloat16          # TPU-native extra (not in numpy proper)


def _np_view(obj):
    """np-typed zero-copy view of a legacy NDArray. The caller's object is
    left untouched (retagging it in place would flip ITS semantics:
    unhashable, bool comparisons, 1-D flatten); the view reads and writes
    through the same payload."""
    if type(obj) is ndarray:
        return obj
    view = NDArray.__getitem__(obj, Ellipsis)
    view.__class__ = ndarray
    return view


def array(obj, dtype=None, ctx=None, copy=True, ndmin=0):
    if isinstance(obj, NDArray):
        if dtype is None and not copy and ndmin == 0:
            return _np_view(obj)
        obj = obj.asnumpy()
    host = _onp.array(obj, dtype=dtype, ndmin=ndmin)
    if dtype is None:
        # reference np.array semantics: dtype-carrying sources keep their
        # dtype; python scalars/lists default to float32 (mx.np deviation
        # from numpy, documented in the reference's multiarray.array)
        dtype = host.dtype if hasattr(obj, "dtype") else _onp.float32
    return as_np_ndarray(_nd_array(host, dtype=dtype, ctx=ctx))


def _creation(jnp_name, jfn=None):
    jfn = jfn or getattr(jnp, jnp_name)

    def fn(*args, ctx=None, **kwargs):
        out = jfn(*args, **kwargs)
        c = ctx or current_context()
        if isinstance(out, tuple):   # index generators (tril_indices, ...)
            return tuple(as_np_ndarray(from_jax(o, ctx=c)) for o in out)
        return as_np_ndarray(from_jax(out, ctx=c))
    fn.__name__ = jnp_name
    fn.__doc__ = "numpy-compatible %s on the current Context" % jnp_name
    return fn


zeros = _creation("zeros")
ones = _creation("ones")
empty = _creation("zeros")          # XLA has no uninitialized alloc
full = _creation("full")
arange = _creation("arange")
linspace = _creation("linspace")
logspace = _creation("logspace")
geomspace = _creation("geomspace")
eye = _creation("eye")
identity = _creation("identity")
tri = _creation("tri")
indices = _creation("indices")
# window functions (reference: mx.np window ops, src/operator/numpy/np_window_op.cc)
bartlett = _creation("bartlett")
blackman = _creation("blackman")
hamming = _creation("hamming")
hanning = _creation("hanning")
kaiser = _creation("kaiser")
# index generators (host-computed, device-resident results)
tril_indices = _creation("tril_indices")
triu_indices = _creation("triu_indices")
diag_indices = _creation("diag_indices")
mask_indices = _creation("mask_indices")


def zeros_like(a, dtype=None, ctx=None):
    return zeros(a.shape, dtype=dtype or a.dtype,
                 ctx=ctx or getattr(a, "context", None))


def ones_like(a, dtype=None, ctx=None):
    return ones(a.shape, dtype=dtype or a.dtype,
                ctx=ctx or getattr(a, "context", None))


def full_like(a, fill_value, dtype=None, ctx=None):
    return full(a.shape, fill_value, dtype=dtype or a.dtype,
                ctx=ctx or getattr(a, "context", None))


empty_like = zeros_like


def asarray(obj, dtype=None):
    if isinstance(obj, NDArray) and dtype is None:
        return _np_view(obj)
    return array(obj, dtype=dtype)


def ascontiguousarray(a, dtype=None):
    return asarray(a, dtype=dtype)   # XLA buffers are always contiguous


asfortranarray = ascontiguousarray


def asnumpy(a):
    return a.asnumpy() if isinstance(a, NDArray) else _onp.asarray(a)


def frombuffer(buffer, dtype=float, count=-1, offset=0):
    return array(_onp.frombuffer(buffer, dtype=dtype, count=count,
                                 offset=offset))


def fromiter(iterable, dtype, count=-1):
    return array(_onp.fromiter(iterable, dtype, count=count))


def fromfunction(function, shape, *, dtype=float, **kwargs):
    return array(_onp.fromfunction(function, shape, dtype=dtype, **kwargs))


def fromstring(string, dtype=float, count=-1, sep=" "):
    return array(_onp.fromstring(string, dtype=dtype, count=count, sep=sep))


def fromfile(file, dtype=float, count=-1, sep="", offset=0):
    return array(_onp.fromfile(file, dtype=dtype, count=count, sep=sep,
                               offset=offset))


def block(arrays):
    def _realize(a):
        if isinstance(a, list):
            return [_realize(x) for x in a]
        return a.data_jax if isinstance(a, NDArray) else a
    return as_np_ndarray(from_jax(jnp.block(_realize(arrays)),
                                  ctx=current_context()))


def tril_indices_from(arr, k=0):
    return tril_indices(arr.shape[-2], k=k, m=arr.shape[-1])


def triu_indices_from(arr, k=0):
    return triu_indices(arr.shape[-2], k=k, m=arr.shape[-1])


def diag_indices_from(arr):
    return diag_indices(arr.shape[0], ndim=arr.ndim)


# ---------------------------------------------------------------------------
# mutating functions — functional jnp result buffer-swapped into the target
# (reference mutates the C++ NDArray payload; here mutation is the engine's
# buffer-swap, so views and the async queue stay consistent)
# ---------------------------------------------------------------------------
def _as_raw(v):
    return v.data_jax if isinstance(v, NDArray) else v


def fill_diagonal(a, val, wrap=False):
    a._check_inplace_ok()
    a._write(jnp.fill_diagonal(a.data_jax, _as_raw(val), wrap=wrap,
                               inplace=False))


def place(arr, mask, vals):
    arr._check_inplace_ok()
    arr._write(jnp.place(arr.data_jax, _as_raw(mask), _as_raw(vals),
                         inplace=False))


def put(a, ind, v, mode="clip"):
    a._check_inplace_ok()
    a._write(jnp.put(a.data_jax, _as_raw(ind), _as_raw(v), mode=mode,
                     inplace=False))


def put_along_axis(arr, indices, values, axis):
    arr._check_inplace_ok()
    arr._write(jnp.put_along_axis(arr.data_jax, _as_raw(indices),
                                  _as_raw(values), axis, inplace=False))


def copyto(dst, src, where=True):
    dst._check_inplace_ok()
    raw = jnp.broadcast_to(jnp.asarray(_as_raw(src), dtype=dst.dtype),
                           dst.shape)
    if where is not True:
        raw = jnp.where(jnp.broadcast_to(_as_raw(where), dst.shape),
                        raw, dst.data_jax)
    dst._write(raw)


# ---------------------------------------------------------------------------
# host-side metadata / inspection — no dispatch (reference: numpy re-exports)
# ---------------------------------------------------------------------------
finfo = _onp.finfo
iinfo = _onp.iinfo
can_cast = _onp.can_cast
promote_types = _onp.promote_types
issubdtype = _onp.issubdtype
isscalar = _onp.isscalar
iterable = _onp.iterable
broadcast_shapes = _onp.broadcast_shapes
isdtype = jnp.isdtype
get_printoptions = _onp.get_printoptions
set_printoptions = _onp.set_printoptions
printoptions = _onp.printoptions
einsum_path = _onp.einsum_path


def result_type(*args):
    return _onp.result_type(*[
        a.dtype if isinstance(a, NDArray) else a for a in args])


def isrealobj(x):
    return not iscomplexobj(x)


def iscomplexobj(x):
    d = x.dtype if isinstance(x, NDArray) else _onp.asarray(x).dtype
    return _onp.issubdtype(d, _onp.complexfloating)


def shape(a):
    return a.shape if hasattr(a, "shape") else _onp.shape(a)


def ndim(a):
    return len(shape(a))


def size(a):
    s = 1
    for d in shape(a):
        s *= d
    return s


def array_repr(arr, *args, **kwargs):
    return _onp.array_repr(asnumpy(arr), *args, **kwargs)


def array_str(a, *args, **kwargs):
    return _onp.array_str(asnumpy(a), *args, **kwargs)


def shares_memory(a, b, max_work=None):
    """True when two arrays alias the same engine payload (view chain)."""
    def _root(x):
        while getattr(x, "_base", None) is not None:
            x = x._base
        return x
    return isinstance(a, NDArray) and isinstance(b, NDArray) and \
        _root(a) is _root(b)


may_share_memory = shares_memory


def save(file, arr):
    _onp.save(file, asnumpy(arr))


def savez(file, *args, **kwargs):
    _onp.savez(file, *[asnumpy(a) for a in args],
               **{k: asnumpy(v) for k, v in kwargs.items()})


def load(file, **kwargs):
    out = _onp.load(file, **kwargs)
    if isinstance(out, _onp.ndarray):
        return array(out)
    return out   # NpzFile: lazily-loaded dict of host arrays


def loadtxt(fname, **kwargs):
    return array(_onp.loadtxt(fname, **kwargs))


def savetxt(fname, X, **kwargs):
    _onp.savetxt(fname, asnumpy(X), **kwargs)


def vectorize(pyfunc, **kwargs):
    vf = _onp.vectorize(pyfunc, **kwargs)

    def wrapped(*args, **kw):
        return array(vf(*[asnumpy(a) if isinstance(a, NDArray) else a
                          for a in args], **kw))
    return wrapped


def r_like(*rows):   # helper for tests; numpy's r_ is an indexer object
    return concatenate([atleast_1d(array(r)) for r in rows])


class _CClass:
    """np.c_ / np.r_ concatenation indexers (reference re-exports numpy's).
    Slice keys expand like numpy's: r_[0:5] -> arange(0, 5); a complex
    step is a linspace point count (r_[0:1:5j])."""
    def __init__(self, axis):
        self.axis = axis

    @staticmethod
    def _expand(a):
        if isinstance(a, slice):
            start = a.start if a.start is not None else 0
            stop = a.stop
            step = a.step if a.step is not None else 1
            if isinstance(step, complex):
                return linspace(start, stop, int(abs(step)))
            return arange(start, stop, step)
        return a if isinstance(a, NDArray) else array(a)

    def __getitem__(self, key):
        if not isinstance(key, tuple):
            key = (key,)
        parts = [self._expand(a) for a in key]
        if self.axis == -1:   # c_: promote 1-D to columns
            parts = [p.reshape(-1, 1) if p.ndim == 1 else p for p in parts]
            return concatenate(parts, axis=1)
        return concatenate([atleast_1d(p) for p in parts], axis=0)


c_ = _CClass(-1)
r_ = _CClass(0)
s_ = _onp.s_
index_exp = _onp.index_exp


from . import random  # noqa: E402
from . import linalg  # noqa: E402

__all__ = ["ndarray", "array", "asarray", "asnumpy", "zeros", "ones",
           "empty", "full", "arange", "linspace", "logspace", "geomspace",
           "eye", "identity", "tri", "indices", "zeros_like", "ones_like",
           "full_like", "empty_like", "frombuffer", "fromiter",
           "fromfunction", "block", "fill_diagonal", "place", "put",
           "put_along_axis", "copyto", "result_type", "finfo", "iinfo",
           "shares_memory", "may_share_memory", "save", "savez", "load",
           "random", "linalg", "fix", "pi", "e", "inf", "nan", "newaxis"] + \
    [n for n, _ in _FUNCS if n in _here]
