"""The dedicated `mx.np.ndarray` type.

reference: python/mxnet/numpy/multiarray.py — a distinct array class with
numpy semantics, separate from the legacy `mx.nd.NDArray`. Here it is a
zero-storage subclass (same buffer-swap payload, same autograd tape, same
async engine semantics) whose operations return `mx.np.ndarray` again and
whose surface follows numpy: `array(...)` repr, `.item()/.tolist()`,
boolean-mask and fancy indexing, zero-dim arrays, numpy-style `astype`,
the full numpy method surface (`argsort/cumsum/std/var/dot/trace/...`),
the full operator-protocol set (`@`, `//`, `divmod`, bitwise, shifts,
in-place variants), and numpy deviations from the legacy namespace
(`flatten()` -> 1-D, `.sort()` in place, bool comparison results).
Retagging (not wrapping) keeps interop free in both directions: an
mx.np.ndarray IS an NDArray everywhere the framework takes one.
"""
from __future__ import annotations

import numpy as _onp

from ..ndarray.ndarray import NDArray, invoke

__all__ = ["ndarray", "as_np_ndarray"]


def _raw_key(key):
    """Realize NDArray (and nested tuple/list) index elements to jax arrays
    so jnp's advanced-indexing engine sees plain arrays. A bare python list
    key is a fancy index in numpy — promote it to an array (jax refuses
    non-tuple sequences outright)."""
    if isinstance(key, NDArray):
        return key.data_jax
    if isinstance(key, tuple):
        return tuple(_raw_key(k) for k in key)
    if isinstance(key, list):
        return _onp.asarray(key)
    return key


class ndarray(NDArray):
    __slots__ = ()

    # -- numpy-flavored surface ---------------------------------------
    def __repr__(self):
        try:
            return repr(self.asnumpy())  # numpy's own 'array(...)' style
        except Exception:
            return "array(<unrealized %s>)" % ("x".join(
                str(d) for d in self.shape))

    def item(self, *args):
        return self.asnumpy().item(*args)

    def tolist(self):
        return self.asnumpy().tolist()

    def tobytes(self, order="C"):
        return self.asnumpy().tobytes(order=order)

    def astype(self, dtype, copy=True):
        out = NDArray.astype(self, dtype)
        return as_np_ndarray(out)

    @property
    def T(self):
        return as_np_ndarray(NDArray.T.fget(self))

    @property
    def itemsize(self):
        return _onp.dtype(self.dtype).itemsize

    @property
    def nbytes(self):
        return self.size * self.itemsize

    @property
    def real(self):
        return as_np_ndarray(invoke("_np_real", self))

    @property
    def imag(self):
        return as_np_ndarray(invoke("_np_imag", self))

    @property
    def flat(self):
        return iter(self.reshape(-1))

    def __getitem__(self, key):
        key = _raw_key(key)
        if NDArray._is_basic_index(key):
            # zero-copy view (reference: NDArray::Slice/At), retagged np
            out = NDArray.__getitem__(self, key)
            out.__class__ = ndarray
            return out
        return as_np_ndarray(NDArray.__getitem__(self, key))

    def __setitem__(self, key, value):
        NDArray.__setitem__(self, _raw_key(key), value)

    def __iter__(self):
        # not a generator: iter() on a 0-d array must raise immediately
        if self.ndim == 0:
            raise TypeError("iteration over a 0-d array")
        return (self[i] for i in range(self.shape[0]))

    def __contains__(self, value):
        return bool((self == value).asnumpy().any())

    def as_nd_ndarray(self):
        """Legacy-namespace view of the same payload (reference:
        ndarray.as_nd_ndarray)."""
        out = NDArray(self._data, ctx=self._ctx, base=self._base,
                      idx=self._idx)
        return out

    def copy(self):
        return as_np_ndarray(NDArray.copy(self))

    # -- numpy deviations from the legacy namespace -------------------
    def flatten(self, order="C"):
        """numpy semantics: full collapse to 1-D (the legacy `mx.nd`
        flatten keeps the batch axis, reference: ndarray.flatten vs
        np.ndarray.flatten)."""
        return as_np_ndarray(invoke("_np_reshape", self, (-1,)))

    def ravel(self, order="C"):
        return self.flatten()

    def reshape(self, *shape, order="C"):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        # pure numpy reshape semantics (no legacy 0/-2/-3 codes)
        return as_np_ndarray(invoke("_np_reshape", self, shape))

    def sort(self, axis=-1, kind=None, order=None):
        """In place, matching numpy (the function form returns a copy)."""
        self._check_inplace_ok()
        res = invoke("_np_sort", self, axis=axis)
        self._write(res._read())

    def fill(self, value):
        self._check_inplace_ok()
        import jax.numpy as jnp
        self._write(jnp.full(self.shape, value, dtype=self.dtype))

    # -- numpy method surface (each rides the registered _np_* op) ----
    def _np1(self, opname, *args, **kwargs):
        return as_np_ndarray(invoke(opname, self, *args, **kwargs))

    def all(self, axis=None, keepdims=False):
        return self._np1("_np_all", axis=axis, keepdims=keepdims)

    def any(self, axis=None, keepdims=False):
        return self._np1("_np_any", axis=axis, keepdims=keepdims)

    def argsort(self, axis=-1, kind=None, order=None):
        return self._np1("_np_argsort", axis=axis)

    def cumsum(self, axis=None, dtype=None):
        return self._np1("_np_cumsum", axis=axis, dtype=dtype)

    def cumprod(self, axis=None, dtype=None):
        return self._np1("_np_cumprod", axis=axis, dtype=dtype)

    def std(self, axis=None, ddof=0, keepdims=False):
        return self._np1("_np_std", axis=axis, ddof=ddof, keepdims=keepdims)

    def var(self, axis=None, ddof=0, keepdims=False):
        return self._np1("_np_var", axis=axis, ddof=ddof, keepdims=keepdims)

    def dot(self, b):
        return self._np1("_np_dot", b)

    def diagonal(self, offset=0, axis1=0, axis2=1):
        return self._np1("_np_diagonal", offset=offset, axis1=axis1,
                         axis2=axis2)

    def trace(self, offset=0, axis1=0, axis2=1):
        return self._np1("_np_trace", offset=offset, axis1=axis1,
                         axis2=axis2)

    def nonzero(self):
        return tuple(as_np_ndarray(o) for o in invoke("_np_nonzero", self))

    def searchsorted(self, v, side="left", sorter=None):
        return self._np1("_np_searchsorted", v, side=side)

    def ptp(self, axis=None, keepdims=False):
        return self._np1("_np_ptp", axis=axis, keepdims=keepdims)

    def conj(self):
        return self._np1("_np_conj")

    conjugate = conj

    def compress(self, condition, axis=None):
        return as_np_ndarray(invoke("_np_compress", condition, self,
                                    axis=axis))

    def repeat(self, repeats, axis=None):
        return self._np1("_np_repeat", repeats=repeats, axis=axis)

    def take(self, indices, axis=None, mode="clip"):
        return self._np1("_np_take", indices, axis=axis, mode=mode)

    def clip(self, a_min=None, a_max=None):
        return self._np1("_np_clip", a_min, a_max)

    def round(self, decimals=0):
        return self._np1("_np_round", decimals=decimals)

    def mean(self, axis=None, dtype=None, keepdims=False):
        kw = {} if dtype is None else {"dtype": dtype}
        return self._np1("_np_mean", axis=axis, keepdims=keepdims, **kw)

    def sum(self, axis=None, dtype=None, keepdims=False):
        kw = {} if dtype is None else {"dtype": dtype}
        return self._np1("_np_sum", axis=axis, keepdims=keepdims, **kw)

    def prod(self, axis=None, dtype=None, keepdims=False):
        kw = {} if dtype is None else {"dtype": dtype}
        return self._np1("_np_prod", axis=axis, keepdims=keepdims, **kw)

    def max(self, axis=None, keepdims=False):
        return self._np1("_np_max", axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return self._np1("_np_min", axis=axis, keepdims=keepdims)

    # -- operator protocols beyond the legacy base --------------------
    def __matmul__(self, other):
        return self._np1("_np_matmul", other)

    def __rmatmul__(self, other):
        return as_np_ndarray(invoke("_np_matmul", other, self))

    def __floordiv__(self, other):
        return self._np1("_np_floor_divide", other)

    def __rfloordiv__(self, other):
        return as_np_ndarray(invoke("_np_floor_divide", other, self))

    def __divmod__(self, other):
        q, r = invoke("_np_divmod", self, other)
        return as_np_ndarray(q), as_np_ndarray(r)

    def __rdivmod__(self, other):
        q, r = invoke("_np_divmod", other, self)
        return as_np_ndarray(q), as_np_ndarray(r)

    def __and__(self, other):
        return self._np1("_np_bitwise_and", other)

    __rand__ = __and__

    def __or__(self, other):
        return self._np1("_np_bitwise_or", other)

    __ror__ = __or__

    def __xor__(self, other):
        return self._np1("_np_bitwise_xor", other)

    __rxor__ = __xor__

    def __invert__(self):
        return self._np1("_np_invert")

    def __lshift__(self, other):
        return self._np1("_np_left_shift", other)

    def __rlshift__(self, other):
        return as_np_ndarray(invoke("_np_left_shift", other, self))

    def __rshift__(self, other):
        return self._np1("_np_right_shift", other)

    def __rrshift__(self, other):
        return as_np_ndarray(invoke("_np_right_shift", other, self))

    def __ifloordiv__(self, other):
        return NDArray._inplace(self, "_np_floor_divide", other)

    def __ipow__(self, other):
        return NDArray._inplace(self, "_np_power", other)

    def __imod__(self, other):
        return NDArray._inplace(self, "_np_mod", other)


def as_np_ndarray(x):
    """Retag NDArray results (and containers of them) as mx.np.ndarray.
    reference: NDArray.as_np_ndarray."""
    if isinstance(x, NDArray):
        if type(x) is NDArray:
            x.__class__ = ndarray
        return x
    if isinstance(x, (list, tuple)):
        return type(x)(as_np_ndarray(v) for v in x)
    return x


def _retag(name):
    base_fn = getattr(NDArray, name)

    def method(self, *args, **kwargs):
        out = base_fn(self, *args, **kwargs)
        # never retag a caller-owned array handed back through the op
        # (copyto/out= return their destination): converting someone
        # else's legacy NDArray in place would change ITS semantics
        if out is self or any(out is a for a in args) \
                or out is kwargs.get("out"):
            return out
        return as_np_ndarray(out)
    method.__name__ = name
    return method


# every op-returning method keeps the np type through the operation
for _name in ["__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
              "__rmul__", "__truediv__", "__rtruediv__", "__mod__",
              "__rmod__", "__pow__", "__rpow__", "__neg__", "__abs__",
              "transpose", "squeeze", "expand_dims", "swapaxes",
              "broadcast_to", "tile", "pick",
              "slice", "slice_axis",
              "argmax", "argmin", "exp", "log", "sqrt", "square",
              "abs", "sign", "flip", "as_in_context",
              "copyto", "detach", "split"]:
    if hasattr(NDArray, _name):
        setattr(ndarray, _name, _retag(_name))


def _bool_cmp(name):
    base_fn = getattr(NDArray, name)

    def method(self, other):
        # numpy semantics: comparisons yield BOOL arrays (usable as masks);
        # the legacy mx.nd namespace yields 0/1 float32 like the reference
        out = base_fn(self, other)
        if isinstance(out, NDArray):
            return as_np_ndarray(out.astype(_onp.bool_))
        return out
    method.__name__ = name
    return method


for _name in ["__eq__", "__ne__", "__lt__", "__le__", "__gt__", "__ge__"]:
    setattr(ndarray, _name, _bool_cmp(_name))

ndarray.__hash__ = None   # numpy arrays are unhashable
