"""`mx.np.linalg` — numpy-compatible linear algebra namespace.

reference: python/mxnet/numpy/linalg.py (mx.np.linalg: norm/svd/inv/
cholesky/... backed by src/operator/numpy/linalg/*). Here each function is
registered as an `_np_linalg_<name>` op wrapping jax.numpy.linalg and
dispatched through imperative `invoke`, so autograd recording, profiling
and the NaiveEngine sync mode apply exactly as for `mx.nd` ops; factor
routines ride XLA's native TPU decompositions. Ops that already exist in
the nd linalg surface (ops/extended.py la_op.cc ports) are aliased, not
re-registered, so there is one canonical implementation per op.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops import registry as _reg
from ..ndarray.ndarray import invoke
from .multiarray import as_np_ndarray

# (name, differentiable, n_outputs) — jnp.linalg callables surfaced 1:1.
# NamedTuple results (SVDResult, QRResult, ...) are normalized to plain
# tuples at registration: the autograd tape hands plain-tuple cotangents to
# jax.vjp, which rejects a pytree-structure mismatch.
_FUNCS = [
    ("norm", True, 1),
    ("svd", True, 3),
    ("cholesky", True, 1),
    ("qr", True, 2),
    ("pinv", True, 1),
    ("solve", True, 1),
    ("lstsq", False, 4),
    ("eig", False, 2),          # complex outputs: non-differentiable here,
    ("eigvals", False, 1),      # matching the reference's FGradient-less ops
    ("eigh", True, 2),
    ("eigvalsh", True, 1),
    ("matrix_rank", False, 1),
    ("matrix_power", True, 1),
    ("multi_dot", True, 1),
    ("tensorinv", True, 1),
    ("tensorsolve", True, 1),
]

# reuse the existing la_op.cc-port ops (ops/extended.py) — one registry
# entry per op; extended.py already returns plain tuples
_ALIASED = {"det": "linalg_det", "slogdet": "linalg_slogdet",
            "inv": "linalg_inverse"}


def _plain(fn, **defaults):
    def impl(*args, **kwargs):
        for k, v in defaults.items():
            kwargs.setdefault(k, v)
        out = fn(*args, **kwargs)
        return tuple(out) if isinstance(out, tuple) else out
    return impl


def _make(op_name, seq, public_name):
    def _fn(*args, **kwargs):
        if seq and len(args) >= 1 and isinstance(args[0], (list, tuple)):
            out = invoke(op_name, *args[0], *args[1:], **kwargs)
        else:
            out = invoke(op_name, *args, **kwargs)
        if isinstance(out, (list, tuple)):
            return type(out)(as_np_ndarray(o) for o in out)
        return as_np_ndarray(out)
    _fn.__name__ = public_name
    _fn.__qualname__ = public_name
    _fn.__doc__ = ("numpy-compatible linalg.%s "
                   "(jax.numpy.linalg.%s under invoke)"
                   % (public_name, public_name))
    return _fn


_here = globals()
for _name, _diff, _nout in _FUNCS:
    _jfn = getattr(jnp.linalg, _name, None)
    if _jfn is None:
        continue
    _op_name = "_np_linalg_" + _name
    if _op_name not in _reg.list_ops():
        if _name == "multi_dot":
            def _seq_impl(*arrays, _jfn=_jfn, **kwargs):
                return _jfn(list(arrays), **kwargs)
            _reg.register(_op_name, differentiable=_diff,
                          num_outputs=_nout)(_seq_impl)
        elif _name == "svd":
            # reference mx.np.linalg.svd returns the REDUCED factorization
            # (and JAX has no vjp for full_matrices=True on non-square)
            _reg.register(_op_name, differentiable=_diff,
                          num_outputs=_nout)(
                _plain(_jfn, full_matrices=False))
        else:
            _reg.register(_op_name, differentiable=_diff,
                          num_outputs=_nout)(_plain(_jfn))
    _here[_name] = _make(_op_name, _name == "multi_dot", _name)

for _name, _existing in _ALIASED.items():
    _here[_name] = _make(_existing, False, _name)

__all__ = sorted([n for n, _, _ in _FUNCS if n in _here] +
                 list(_ALIASED))
