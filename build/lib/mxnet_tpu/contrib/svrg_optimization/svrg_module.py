"""SVRGModule: Module with Stochastic Variance Reduced Gradient updates.

reference: python/mxnet/contrib/svrg_optimization/svrg_module.py —
SVRGModule(symbol, ..., update_freq) keeps a second executor at the
snapshot parameters w0; `update_full_grads(train_data)` accumulates
mu = mean_batch g(w0, batch); each training step rewrites the gradient
buffers to g(w, b) - g(w0, b) + mu before the ordinary optimizer update.

The aux executor rides the same jit/XLA program cache as the primary
(identical symbol -> identical compiled step), so the extra
forward/backward costs one cached program launch, not a recompile.
"""
import logging

from ...module.module import Module
from ... import metric as _metric


class SVRGModule(Module):
    """reference: svrg_module.py (SVRGModule). `update_freq` is the number
    of epochs between full-gradient snapshots."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None,
                 update_freq=2):
        super().__init__(symbol, data_names, label_names, logger=logger,
                         context=context, work_load_list=work_load_list,
                         fixed_param_names=fixed_param_names,
                         state_names=state_names, group2ctxs=group2ctxs,
                         compression_params=compression_params)
        if not isinstance(update_freq, int) or update_freq < 1:
            raise ValueError("update_freq must be a positive int, got %r"
                             % (update_freq,))
        self.update_freq = update_freq
        self._mod_aux = Module(symbol, data_names, label_names,
                               logger=logger, context=context,
                               work_load_list=work_load_list,
                               fixed_param_names=fixed_param_names,
                               state_names=state_names, group2ctxs=group2ctxs,
                               compression_params=compression_params)
        self._full_grads = None          # name -> mu NDArray (host of truth)

    # -- lifecycle ----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        super().bind(data_shapes, label_shapes, for_training,
                     inputs_need_grad, force_rebind, shared_module, grad_req)
        if for_training:
            self._mod_aux.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind, shared_module,
                               grad_req)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        super().init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params,
                            allow_missing=allow_missing,
                            force_init=force_init, allow_extra=allow_extra)
        if self._mod_aux.binded:
            args, auxs = self.get_params()
            self._mod_aux.init_params(arg_params=args, aux_params=auxs,
                                      allow_missing=False, force_init=True)

    # -- SVRG core ----------------------------------------------------------
    def update_full_grads(self, train_data):
        """Snapshot w0 <- w and accumulate mu = (1/nbatch) sum g(w0, b).
        reference: SVRGModule.update_full_grads."""
        assert self.binded and self.params_initialized
        args, auxs = self.get_params()
        self._mod_aux.set_params(arg_params=args, aux_params=auxs)
        train_data.reset()
        accum = {}
        nbatch = 0
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            for name, grads in zip(self._mod_aux._exec_group.param_names,
                                   self._mod_aux._exec_group.grad_arrays):
                total = None
                for g in grads:
                    if g is None:
                        continue
                    total = (g + 0.0) if total is None else total + g
                if total is None:
                    continue
                if name in accum:
                    accum[name] = accum[name] + total
                else:
                    accum[name] = total
            nbatch += 1
        assert nbatch > 0, "update_full_grads: empty data iterator"
        self._full_grads = {name: a / float(nbatch)
                            for name, a in accum.items()}

    def _svrg_grads_updated(self):
        return self._full_grads is not None

    def forward_backward(self, data_batch):
        """forward+backward on BOTH executors, then rewrite the primary
        grad buffers to the variance-reduced form.
        reference: SVRGModule.forward_backward + _update_svrg_gradients."""
        super().forward(data_batch, is_train=True)
        super().backward()
        if not self._svrg_grads_updated():
            return
        self._mod_aux.forward(data_batch, is_train=True)
        self._mod_aux.backward()
        for name, grads, grads0 in zip(
                self._exec_group.param_names,
                self._exec_group.grad_arrays,
                self._mod_aux._exec_group.grad_arrays):
            mu = self._full_grads.get(name)
            if mu is None:
                continue
            for g, g0 in zip(grads, grads0):
                if g is None or g0 is None:
                    continue
                g[:] = g - g0 + mu.as_in_context(g.context)

    # -- training loop ------------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The base fit loop with a full-gradient snapshot every
        `update_freq` epochs. reference: SVRGModule.fit."""
        assert num_epoch is not None, "please specify number of epochs"
        from ... import initializer as _init
        if initializer is None:
            initializer = _init.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            eval_metric.reset()
            if (epoch - begin_epoch) % self.update_freq == 0:
                self.update_full_grads(train_data)
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                self.forward_backward(data_batch)
                self.update()
                if isinstance(data_batch, list):
                    self.update_metric(eval_metric,
                                       [db.label for db in data_batch],
                                       pre_sliced=True)
                else:
                    self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    from ...model import BatchEndParam
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for cb in (batch_end_callback
                               if isinstance(batch_end_callback,
                                             (list, tuple))
                               else [batch_end_callback]):
                        cb(params)
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in (epoch_end_callback
                           if isinstance(epoch_end_callback, (list, tuple))
                           else [epoch_end_callback]):
                    cb(epoch, self.symbol, arg_p, aux_p)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
