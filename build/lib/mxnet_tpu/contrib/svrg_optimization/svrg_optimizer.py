"""SVRG optimizer wrapper.

reference: python/mxnet/contrib/svrg_optimization/svrg_optimizer.py
(_SVRGOptimizer) — a dispatching optimizer: full-gradient keys are plain
assignments (the kvstore stores mu), everything else delegates to the
user's base optimizer. Kept for API parity and for users driving the
kvstore protocol directly; SVRGModule itself applies the variance
reduction in the gradient buffers and only needs the base optimizer.
"""
from ... import optimizer as _opt
from ...optimizer import Optimizer


@Optimizer.register
class SVRGOptimizer(Optimizer):
    """Dispatch optimizer: `index >= full_idx_offset` (or names ending in
    ``_full``) assign the pushed value into the stored weight (mu
    bookkeeping); all other keys delegate to ``default_optimizer``.

    Parameters
    ----------
    default_optimizer : str or Optimizer
        The real update rule (e.g. "sgd").
    full_idx_offset : int
        Keys at or above this index hold full gradients (assignment
        semantics). 0 disables index-based detection.
    """

    def __init__(self, default_optimizer="sgd", full_idx_offset=0,
                 **kwargs):
        # base-Optimizer kwargs are shared with the delegate
        super().__init__(**{k: v for k, v in kwargs.items()
                            if k in ("rescale_grad", "param_idx2name", "wd",
                                     "clip_gradient", "learning_rate",
                                     "lr_scheduler", "begin_num_update",
                                     "multi_precision")})
        if isinstance(default_optimizer, Optimizer):
            self.default_opt = default_optimizer
        else:
            self.default_opt = _opt.create(default_optimizer, **kwargs)
        self.full_idx_offset = full_idx_offset

    def _is_full_key(self, index):
        name = self.idx2name.get(index)
        if name is not None and str(name).endswith("_full"):
            return True
        return self.full_idx_offset > 0 and index >= self.full_idx_offset

    def create_state(self, index, weight):
        if self._is_full_key(index):
            return None
        return self.default_opt.create_state(index, weight)

    def update(self, index, weight, grad, state):
        if self._is_full_key(index):
            # assignment semantics: the "weight" slot stores mu
            weight[:] = grad
            return
        self.default_opt.update(index, weight, grad, state)
