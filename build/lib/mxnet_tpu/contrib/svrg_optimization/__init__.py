"""SVRG (Stochastic Variance Reduced Gradient) optimization.

reference: python/mxnet/contrib/svrg_optimization/ (SVRGModule,
_SVRGOptimizer) — implements Johnson & Zhang (NIPS'13): every
`update_freq` epochs snapshot the parameters w0 and accumulate the full
gradient mu = (1/N) sum_i g(w0, batch_i); each step then descends along
  g_vr = g(w, batch) - g(w0, batch) + mu
whose variance vanishes as w -> w*, permitting constant step sizes.

TPU-first shape: the reference routes mu through special kvstore keys
("key_full") consumed by an assignment optimizer; here mu lives host-side
in the module and the variance-reduced gradient is formed in the grad
buffers before the ordinary update — one less wire protocol, identical
math, and the base optimizer stays an unmodified registry citizen.
"""
from .svrg_module import SVRGModule
from .svrg_optimizer import SVRGOptimizer

__all__ = ["SVRGModule", "SVRGOptimizer"]
