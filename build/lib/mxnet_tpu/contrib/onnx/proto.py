"""Minimal protobuf wire codec for the ONNX message subset.

reference: python/mxnet/contrib/onnx/ depends on the `onnx` pip package;
that package is not in this image, and the wire format is small, so the
subset of onnx.proto this exporter emits (ModelProto/GraphProto/NodeProto/
AttributeProto/TensorProto/ValueInfoProto/TypeProto) is encoded directly.
Field numbers follow onnx.proto (stable since IR v3); files produced here
load in stock onnx/onnxruntime, and import_model reads both our output
and files produced by onnx.helper.

Wire format: varint (wire 0) for ints/enums, fixed32 (wire 5) for floats,
length-delimited (wire 2) for strings/bytes/messages/packed-repeated.
"""
from __future__ import annotations

import struct

__all__ = ["Message", "Field", "ModelProto", "GraphProto", "NodeProto",
           "AttributeProto", "TensorProto", "ValueInfoProto", "TypeProto",
           "TensorTypeProto", "TensorShapeProto", "Dimension",
           "OperatorSetIdProto", "DT", "AT"]


# TensorProto.DataType / AttributeProto.AttributeType enums (onnx.proto)
class DT:
    FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64, STRING, BOOL, \
        FLOAT16, DOUBLE, UINT32, UINT64, COMPLEX64, COMPLEX128, BFLOAT16 \
        = range(1, 17)


class AT:
    FLOAT, INT, STRING, TENSOR, GRAPH = 1, 2, 3, 4, 5
    FLOATS, INTS, STRINGS, TENSORS, GRAPHS = 6, 7, 8, 9, 10


def _uvarint(v):
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _varint(v):
    if v < 0:
        v += 1 << 64          # two's-complement 64-bit
    return _uvarint(v)


def _read_uvarint(buf, pos):
    shift = 0
    val = 0
    while True:
        b = buf[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7


def _to_signed(v):
    return v - (1 << 64) if v >= (1 << 63) else v


class Field:
    def __init__(self, num, kind, repeated=False, msg=None):
        self.num = num
        self.kind = kind        # "int" | "float" | "string" | "bytes" | "msg"
        self.repeated = repeated
        self.msg = msg          # Message subclass when kind == "msg"


class Message:
    SCHEMA: dict = {}

    def __init__(self, **kwargs):
        for name, f in self.SCHEMA.items():
            setattr(self, name, [] if f.repeated else None)
        for k, v in kwargs.items():
            if k not in self.SCHEMA:
                raise TypeError("%s has no field %r" % (type(self).__name__,
                                                        k))
            setattr(self, k, v)

    # -- encode --------------------------------------------------------
    def encode(self):
        out = bytearray()
        for name, f in self.SCHEMA.items():
            val = getattr(self, name)
            if val is None or (f.repeated and not val):
                continue
            vals = val if f.repeated else [val]
            if f.kind == "int":
                if f.repeated:          # packed
                    body = b"".join(_varint(int(v)) for v in vals)
                    out += _uvarint((f.num << 3) | 2)
                    out += _uvarint(len(body)) + body
                else:
                    out += _uvarint(f.num << 3) + _varint(int(vals[0]))
            elif f.kind == "float":
                if f.repeated:          # packed fixed32
                    body = b"".join(struct.pack("<f", float(v))
                                    for v in vals)
                    out += _uvarint((f.num << 3) | 2)
                    out += _uvarint(len(body)) + body
                else:
                    out += _uvarint((f.num << 3) | 5)
                    out += struct.pack("<f", float(vals[0]))
            else:
                for v in vals:
                    if f.kind == "msg":
                        body = v.encode()
                    elif f.kind == "string":
                        body = v.encode("utf-8") if isinstance(v, str) else v
                    else:                     # bytes
                        body = bytes(v)
                    out += _uvarint((f.num << 3) | 2)
                    out += _uvarint(len(body)) + body
        return bytes(out)

    # -- decode --------------------------------------------------------
    @classmethod
    def decode(cls, buf):
        self = cls()
        by_num = {f.num: (name, f) for name, f in cls.SCHEMA.items()}
        pos = 0
        n = len(buf)
        while pos < n:
            tag, pos = _read_uvarint(buf, pos)
            num, wire = tag >> 3, tag & 7
            entry = by_num.get(num)
            if entry is None:               # skip unknown field
                if wire == 0:
                    _, pos = _read_uvarint(buf, pos)
                elif wire == 2:
                    ln, pos = _read_uvarint(buf, pos)
                    pos += ln
                elif wire == 5:
                    pos += 4
                elif wire == 1:
                    pos += 8
                else:
                    raise ValueError("bad wire type %d" % wire)
                continue
            name, f = entry
            if wire == 0:
                raw, pos = _read_uvarint(buf, pos)
                val = _to_signed(raw) if f.kind == "int" else raw
                self._store(name, f, val)
            elif wire == 5:
                (val,) = struct.unpack_from("<f", buf, pos)
                pos += 4
                self._store(name, f, val)
            elif wire == 1:
                (val,) = struct.unpack_from("<d", buf, pos)
                pos += 8
                self._store(name, f, val)
            elif wire == 2:
                ln, pos = _read_uvarint(buf, pos)
                chunk = buf[pos:pos + ln]
                pos += ln
                if f.kind == "msg":
                    self._store(name, f, f.msg.decode(chunk))
                elif f.kind == "string":
                    self._store(name, f, chunk.decode("utf-8",
                                                      errors="replace"))
                elif f.kind == "bytes":
                    self._store(name, f, bytes(chunk))
                elif f.kind == "int" and f.repeated:    # packed
                    p2 = 0
                    while p2 < len(chunk):
                        raw, p2 = _read_uvarint(chunk, p2)
                        getattr(self, name).append(_to_signed(raw))
                elif f.kind == "float" and f.repeated:  # packed fixed32
                    for i in range(0, len(chunk) - 3, 4):
                        getattr(self, name).append(
                            struct.unpack_from("<f", chunk, i)[0])
                else:
                    raise ValueError("field %s: unexpected wire 2" % name)
            else:
                raise ValueError("bad wire type %d" % wire)
        return self

    def _store(self, name, f, val):
        if f.repeated:
            getattr(self, name).append(val)
        else:
            setattr(self, name, val)


class OperatorSetIdProto(Message):
    SCHEMA = {"domain": Field(1, "string"), "version": Field(2, "int")}


class Dimension(Message):
    SCHEMA = {"dim_value": Field(1, "int"), "dim_param": Field(2, "string")}


class TensorShapeProto(Message):
    SCHEMA = {"dim": Field(1, "msg", repeated=True, msg=Dimension)}


class TensorTypeProto(Message):
    SCHEMA = {"elem_type": Field(1, "int"),
              "shape": Field(2, "msg", msg=TensorShapeProto)}


class TypeProto(Message):
    SCHEMA = {"tensor_type": Field(1, "msg", msg=TensorTypeProto)}


class ValueInfoProto(Message):
    SCHEMA = {"name": Field(1, "string"),
              "type": Field(2, "msg", msg=TypeProto),
              "doc_string": Field(3, "string")}


class TensorProto(Message):
    SCHEMA = {
        "dims": Field(1, "int", repeated=True),
        "data_type": Field(2, "int"),
        "float_data": Field(4, "float", repeated=True),
        "int32_data": Field(5, "int", repeated=True),
        "string_data": Field(6, "bytes", repeated=True),
        "int64_data": Field(7, "int", repeated=True),
        "name": Field(8, "string"),
        "raw_data": Field(9, "bytes"),
        "doc_string": Field(12, "string"),
    }


class AttributeProto(Message):
    SCHEMA = {
        "name": Field(1, "string"),
        "f": Field(2, "float"),
        "i": Field(3, "int"),
        "s": Field(4, "bytes"),
        "t": Field(5, "msg", msg=TensorProto),
        "floats": Field(7, "float", repeated=True),
        "ints": Field(8, "int", repeated=True),
        "strings": Field(9, "bytes", repeated=True),
        "tensors": Field(10, "msg", repeated=True, msg=TensorProto),
        "doc_string": Field(13, "string"),
        "type": Field(20, "int"),
    }


class NodeProto(Message):
    SCHEMA = {
        "input": Field(1, "string", repeated=True),
        "output": Field(2, "string", repeated=True),
        "name": Field(3, "string"),
        "op_type": Field(4, "string"),
        "attribute": Field(5, "msg", repeated=True, msg=AttributeProto),
        "doc_string": Field(6, "string"),
        "domain": Field(7, "string"),
    }


class GraphProto(Message):
    SCHEMA = {
        "node": Field(1, "msg", repeated=True, msg=NodeProto),
        "name": Field(2, "string"),
        "initializer": Field(5, "msg", repeated=True, msg=TensorProto),
        "doc_string": Field(10, "string"),
        "input": Field(11, "msg", repeated=True, msg=ValueInfoProto),
        "output": Field(12, "msg", repeated=True, msg=ValueInfoProto),
        "value_info": Field(13, "msg", repeated=True, msg=ValueInfoProto),
    }


class ModelProto(Message):
    SCHEMA = {
        "ir_version": Field(1, "int"),
        "producer_name": Field(2, "string"),
        "producer_version": Field(3, "string"),
        "domain": Field(4, "string"),
        "model_version": Field(5, "int"),
        "doc_string": Field(6, "string"),
        "graph": Field(7, "msg", msg=GraphProto),
        "opset_import": Field(8, "msg", repeated=True,
                              msg=OperatorSetIdProto),
    }
