"""ONNX → symbol graph import.

reference: python/mxnet/contrib/onnx/onnx2mx/ (import_model,
GraphProto.from_onnx) — walks the ONNX node list, builds mx.sym ops,
splits initializers into arg/aux params. Covers the op set
`mx2onnx.export_model` emits (and the same ops from files produced by
stock onnx tooling at opset >= 11).
"""
from __future__ import annotations

import numpy as _onp

from . import proto as P

__all__ = ["import_model"]

import ml_dtypes as _ml_dtypes

_NP_DTYPE = {
    P.DT.FLOAT: _onp.float32, P.DT.DOUBLE: _onp.float64,
    P.DT.FLOAT16: _onp.float16, P.DT.INT32: _onp.int32,
    P.DT.INT64: _onp.int64, P.DT.INT8: _onp.int8, P.DT.UINT8: _onp.uint8,
    P.DT.BOOL: _onp.bool_,
    P.DT.BFLOAT16: _ml_dtypes.bfloat16,   # the flagship TPU dtype
}


def _tensor_to_np(t):
    dtype = _NP_DTYPE[t.data_type]
    if t.raw_data:
        arr = _onp.frombuffer(t.raw_data, dtype=dtype)
    elif t.float_data:
        arr = _onp.asarray(t.float_data, dtype=dtype)
    elif t.int64_data:
        arr = _onp.asarray(t.int64_data, dtype=dtype)
    elif t.int32_data:
        arr = _onp.asarray(t.int32_data, dtype=dtype)
    else:
        arr = _onp.zeros(0, dtype)
    return arr.reshape(tuple(t.dims)) if t.dims else arr.reshape(())


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == P.AT.INT:
            out[a.name] = a.i
        elif a.type == P.AT.FLOAT:
            out[a.name] = a.f
        elif a.type == P.AT.STRING:
            out[a.name] = a.s.decode("utf-8")
        elif a.type == P.AT.INTS:
            out[a.name] = tuple(a.ints)
        elif a.type == P.AT.FLOATS:
            out[a.name] = tuple(a.floats)
        elif a.type == P.AT.TENSOR:
            out[a.name] = _tensor_to_np(a.t)
    return out


def import_model(onnx_file):
    """Load an ONNX file → (sym, arg_params, aux_params).

    reference: mx.contrib.onnx.import_model."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    with open(onnx_file, "rb") as f:
        model = P.ModelProto.decode(f.read())
    g = model.graph

    consts = {t.name: _tensor_to_np(t) for t in g.initializer}
    sym_of = {}               # value name -> Symbol
    used_params = {}          # param name -> numpy (reached via Variable)
    aux_names = set()

    def as_sym(name):
        if name in sym_of:
            return sym_of[name]
        v = mx.sym.Variable(name)
        sym_of[name] = v
        if name in consts:
            used_params[name] = consts[name]
        return v

    for vi in g.input:
        if vi.name not in consts:
            as_sym(vi.name)

    # consumers per value name as (op_type, input_slot): int Casts may
    # only collapse to identity when they feed Gather's INDICES slot
    # exclusively (mx.take accepts float indices); a cast feeding data
    # carries truncation semantics
    consumer_ops = {}
    for node_ in g.node:
        for slot, x in enumerate(node_.input):
            consumer_ops.setdefault(x, []).append((node_.op_type, slot))

    def sym_pads(a, k):
        """ONNX pads = [begin..., end...]; the symmetric form maps to the
        mx `pad` attr. Asymmetric padding has no Pooling/Convolution
        equivalent — refuse instead of silently truncating."""
        pads = tuple(a.get("pads", (0,) * 2 * k))
        begin, end = pads[:k], pads[k:2 * k]
        if begin != end:
            raise NotImplementedError(
                "ONNX import: asymmetric pads %s are not supported"
                % (pads,))
        return begin

    def pool(node, a, op_kwargs):
        kernel = tuple(a["kernel_shape"])
        kw = dict(kernel=kernel,
                  stride=tuple(a.get("strides", (1,) * len(kernel))),
                  pad=sym_pads(a, len(kernel)), **op_kwargs)
        return mx.sym.Pooling(as_sym(node.input[0]), name=node.name, **kw)

    for node in g.node:
        op = node.op_type
        a = _attrs(node)
        ins = node.input
        name = node.name or (node.output[0] + "_op")

        if op == "Conv":
            kernel = tuple(a["kernel_shape"])
            args = [as_sym(x) for x in ins]
            num_filter = consts[ins[1]].shape[0] if ins[1] in consts else 0
            out = mx.sym.Convolution(
                *args, name=name, kernel=kernel,
                stride=tuple(a.get("strides", (1,) * len(kernel))),
                pad=sym_pads(a, len(kernel)),
                dilate=tuple(a.get("dilations", (1,) * len(kernel))),
                num_group=a.get("group", 1), num_filter=num_filter,
                no_bias=len(ins) == 2)
        elif op == "ConvTranspose":
            kernel = tuple(a["kernel_shape"])
            args = [as_sym(x) for x in ins]
            num_filter = consts[ins[1]].shape[1] if ins[1] in consts else 0
            out = mx.sym.Deconvolution(
                *args, name=name, kernel=kernel,
                stride=tuple(a.get("strides", (1,) * len(kernel))),
                pad=sym_pads(a, len(kernel)),
                num_filter=num_filter, no_bias=len(ins) == 2)
        elif op == "Gemm":
            alpha = a.get("alpha", 1.0)
            beta = a.get("beta", 1.0)
            trans_a = a.get("transA", 0)
            trans_b = a.get("transB", 0)
            w = consts.get(ins[1])
            if (trans_b and not trans_a and alpha == 1.0 and beta == 1.0
                    and w is not None):
                # the FullyConnected layout (Y = X @ W.T + b): fast path
                out = mx.sym.FullyConnected(
                    *[as_sym(x) for x in ins], name=name,
                    num_hidden=w.shape[0], no_bias=len(ins) == 2,
                    flatten=False)
            else:
                # general Gemm: alpha*op(A)@op(B) + beta*C
                A = as_sym(ins[0])
                if trans_a:
                    A = mx.sym.transpose(A, name=name + "_tA")
                B = as_sym(ins[1])
                if trans_b:
                    B = mx.sym.transpose(B, name=name + "_tB")
                out = mx.sym.dot(A, B, name=name + "_mm")
                if alpha != 1.0:
                    out = out * alpha
                if len(ins) > 2:
                    C = as_sym(ins[2])
                    out = mx.sym.broadcast_add(
                        out, C * beta if beta != 1.0 else C, name=name)
        elif op == "MatMul":
            out = mx.sym.dot(as_sym(ins[0]), as_sym(ins[1]), name=name)
        elif op == "BatchNormalization":
            for aux in ins[3:5]:
                aux_names.add(aux)
            out = mx.sym.BatchNorm(*[as_sym(x) for x in ins], name=name,
                                   eps=a.get("epsilon", 1e-5),
                                   momentum=a.get("momentum", 0.9),
                                   fix_gamma=False)
        elif op == "MaxPool":
            out = pool(node, a, {"pool_type": "max"})
        elif op == "AveragePool":
            # ONNX defaults count_include_pad=0; mx Pooling defaults True
            out = pool(node, a, {"pool_type": "avg", "count_include_pad":
                                 bool(a.get("count_include_pad", 0))})
        elif op == "GlobalMaxPool":
            out = mx.sym.Pooling(as_sym(ins[0]), name=name, kernel=(1, 1),
                                 pool_type="max", global_pool=True)
        elif op == "GlobalAveragePool":
            out = mx.sym.Pooling(as_sym(ins[0]), name=name, kernel=(1, 1),
                                 pool_type="avg", global_pool=True)
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Softsign"):
            act = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
                   "Softplus": "softrelu", "Softsign": "softsign"}[op]
            out = mx.sym.Activation(as_sym(ins[0]), act_type=act, name=name)
        elif op == "LeakyRelu":
            out = mx.sym.LeakyReLU(as_sym(ins[0]), act_type="leaky",
                                   slope=a.get("alpha", 0.01), name=name)
        elif op == "Elu":
            out = mx.sym.LeakyReLU(as_sym(ins[0]), act_type="elu",
                                   slope=a.get("alpha", 1.0), name=name)
        elif op == "Erf":
            out = mx.sym.erf(as_sym(ins[0]), name=name)
        elif op == "PRelu":
            out = mx.sym.LeakyReLU(as_sym(ins[0]), as_sym(ins[1]),
                                   act_type="prelu", name=name)
        elif op == "Exp":
            out = mx.sym.exp(as_sym(ins[0]), name=name)
        elif op == "Log":
            out = mx.sym.log(as_sym(ins[0]), name=name)
        elif op == "Sqrt":
            out = mx.sym.sqrt(as_sym(ins[0]), name=name)
        elif op == "Softmax":
            out = mx.sym.softmax(as_sym(ins[0]), axis=a.get("axis", -1),
                                 name=name)
        elif op == "LogSoftmax":
            out = mx.sym.log_softmax(as_sym(ins[0]),
                                     axis=a.get("axis", -1), name=name)
        elif op == "Dropout":
            ratio = a.get("ratio", 0.5)
            if len(ins) > 1 and ins[1] in consts:
                ratio = float(consts[ins[1]])
            out = mx.sym.Dropout(as_sym(ins[0]), p=ratio, name=name)
        elif op == "Flatten":
            out = mx.sym.Flatten(as_sym(ins[0]), name=name)
        elif op == "Reshape":
            shape = tuple(int(x) for x in consts[ins[1]])
            out = mx.sym.reshape(as_sym(ins[0]), shape=shape, name=name)
        elif op == "Transpose":
            kw = {"axes": tuple(a["perm"])} if "perm" in a else {}
            out = mx.sym.transpose(as_sym(ins[0]), name=name, **kw)
        elif op == "Unsqueeze":
            axes = (tuple(a["axes"]) if "axes" in a
                    else tuple(int(x) for x in consts[ins[1]]))
            out = as_sym(ins[0])
            # ONNX axes are relative to the OUTPUT rank (negatives legal);
            # resolving them needs the input rank
            out_rank = None
            try:
                shp, _, _ = out.infer_shape()
                out_rank = len(shp[0]) + len(axes) if shp else None
            except Exception:
                pass
            norm = []
            for ax in axes:
                ax = int(ax)
                if ax < 0:
                    if out_rank is None:
                        raise NotImplementedError(
                            "ONNX import: negative Unsqueeze axes need "
                            "inferable input shape")
                    ax += out_rank
                norm.append(ax)
            for k, ax in enumerate(sorted(norm)):
                out = mx.sym.expand_dims(out, axis=ax,
                                         name="%s_%d" % (name, k))
        elif op == "Squeeze":
            axes = (tuple(a["axes"]) if "axes" in a
                    else (tuple(int(x) for x in consts[ins[1]])
                          if len(ins) > 1 else None))
            out = mx.sym.squeeze(as_sym(ins[0]),
                                 axis=(axes if axes is None else
                                       tuple(axes)), name=name)
        elif op == "Concat":
            out = mx.sym.concat(*[as_sym(x) for x in ins],
                                dim=a.get("axis", 1), name=name)
        elif op in ("Add", "Sub", "Mul", "Div"):
            fn = {"Add": mx.sym.broadcast_add, "Sub": mx.sym.broadcast_sub,
                  "Mul": mx.sym.broadcast_mul,
                  "Div": mx.sym.broadcast_div}[op]
            out = fn(as_sym(ins[0]), as_sym(ins[1]), name=name)
        elif op == "Sum":
            out = mx.sym.add_n(*[as_sym(x) for x in ins], name=name)
        elif op == "Gather":
            out = mx.sym.take(as_sym(ins[0]), as_sym(ins[1]),
                              axis=a.get("axis", 0), name=name)
        elif op == "Cast":
            to = a.get("to", P.DT.FLOAT)
            feeds = [c for o in node.output
                     for c in consumer_ops.get(o, [])]
            if to in (P.DT.INT64, P.DT.INT32) and feeds and \
                    all(c == ("Gather", 1) for c in feeds):
                # pure index cast (the Gather pattern): mx.take accepts
                # float indices, so the cast collapses
                out = as_sym(ins[0])
            elif to in (P.DT.INT64, P.DT.INT32):
                out = mx.sym.Cast(as_sym(ins[0]),
                                  dtype={P.DT.INT64: "int64",
                                         P.DT.INT32: "int32"}[to],
                                  name=name)
            else:
                dt = {P.DT.FLOAT: "float32", P.DT.FLOAT16: "float16",
                      P.DT.DOUBLE: "float64", P.DT.BFLOAT16: "bfloat16",
                      P.DT.UINT8: "uint8", P.DT.INT8: "int8",
                      P.DT.BOOL: "bool"}.get(to)
                if dt is None:
                    raise NotImplementedError(
                        "ONNX import: Cast to data_type %d" % to)
                out = mx.sym.Cast(as_sym(ins[0]), dtype=dt, name=name)
        elif op == "Identity":
            out = as_sym(ins[0])
        else:
            raise NotImplementedError(
                "ONNX import: unsupported op %r" % op)

        for o in node.output:
            sym_of[o] = out

    outs = [sym_of[o.name] for o in g.output]
    sym = outs[0] if len(outs) == 1 else mx.sym.Group(outs)

    arg_params, aux_params = {}, {}
    wanted = set(sym.list_arguments()) | set(
        getattr(sym, "list_auxiliary_states", lambda: [])())
    for pname, arr in used_params.items():
        if pname not in wanted:
            continue
        target = aux_params if pname in aux_names else arg_params
        target[pname] = nd.array(arr, dtype=arr.dtype)
    return sym, arg_params, aux_params
