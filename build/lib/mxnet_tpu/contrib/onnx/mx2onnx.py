"""Symbol graph → ONNX export.

reference: python/mxnet/contrib/onnx/mx2onnx/ (export_model,
MXNetGraph.create_onnx_graph_proto) — per-op converter functions walking
the symbol's JSON node list. Same architecture here: `@mx_op` converters
keyed by the registry op name, emitting opset-13 nodes; parameters become
initializers (raw little-endian bytes).
"""
from __future__ import annotations

import ast
import json

import numpy as _onp

from . import proto as P

__all__ = ["export_model"]

_OPSET = 13
_CONVERTERS = {}

_DTYPE_MAP = {
    "float32": P.DT.FLOAT, "float64": P.DT.DOUBLE, "float16": P.DT.FLOAT16,
    "bfloat16": P.DT.BFLOAT16, "int32": P.DT.INT32, "int64": P.DT.INT64,
    "int8": P.DT.INT8, "uint8": P.DT.UINT8, "bool": P.DT.BOOL,
}


def mx_op(*names):
    def deco(fn):
        for n in names:
            _CONVERTERS[n] = fn
        return fn
    return deco


def _parse_attrs(attrs):
    out = {}
    for k, v in (attrs or {}).items():
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def _attr_i(name, v):
    return P.AttributeProto(name=name, type=P.AT.INT, i=int(v))


def _attr_f(name, v):
    return P.AttributeProto(name=name, type=P.AT.FLOAT, f=float(v))


def _attr_s(name, v):
    return P.AttributeProto(name=name, type=P.AT.STRING,
                            s=str(v).encode("utf-8"))


def _attr_ints(name, vs):
    return P.AttributeProto(name=name, type=P.AT.INTS,
                            ints=[int(x) for x in vs])


def _tensor(name, arr):
    arr = _onp.ascontiguousarray(arr)
    dt = _DTYPE_MAP[str(arr.dtype)]
    return P.TensorProto(name=name, dims=list(arr.shape), data_type=dt,
                         raw_data=arr.tobytes())


class _Builder:
    """Accumulates nodes/initializers; converters call back into it."""

    def __init__(self, params=None):
        self.nodes = []
        self.initializers = []
        self.params = params or {}    # host numpy params, for shape lookups
        self.np_dtype = _onp.float32  # model dtype, set by export_model
        self._uid = 0

    def add(self, op_type, inputs, name, outputs=None, attrs=()):
        outs = outputs or [name]
        self.nodes.append(P.NodeProto(op_type=op_type, name=name,
                                      input=list(inputs), output=outs,
                                      attribute=list(attrs)))
        return outs[0]

    def const(self, name, arr):
        self.initializers.append(_tensor(name, _onp.asarray(arr)))
        return name

    def tmp(self, base):
        self._uid += 1
        return "%s__%d" % (base, self._uid)


def _tuple2(v, default):
    """Normalize an mx stride/pad/dilate attr to len(default) entries
    (scalar attrs broadcast to the kernel rank, not to 2)."""
    if v is None:
        return default
    if isinstance(v, int):
        return (v,) * len(default)
    return tuple(v)


# ---------------------------------------------------------------- convs
@mx_op("Convolution")
def _conv(b, name, ins, a):
    kernel = tuple(a["kernel"])
    stride = _tuple2(a.get("stride"), (1,) * len(kernel))
    pad = _tuple2(a.get("pad"), (0,) * len(kernel))
    dilate = _tuple2(a.get("dilate"), (1,) * len(kernel))
    attrs = [_attr_ints("kernel_shape", kernel),
             _attr_ints("strides", stride),
             _attr_ints("pads", list(pad) * 2),
             _attr_ints("dilations", dilate),
             _attr_i("group", a.get("num_group", 1))]
    return b.add("Conv", ins, name, attrs=attrs)


@mx_op("Deconvolution")
def _deconv(b, name, ins, a):
    kernel = tuple(a["kernel"])
    if a.get("target_shape"):
        raise NotImplementedError(
            "ONNX export: Deconvolution target_shape is not supported")
    stride = _tuple2(a.get("stride"), (1,) * len(kernel))
    pad = _tuple2(a.get("pad"), (0,) * len(kernel))
    dilate = _tuple2(a.get("dilate"), (1,) * len(kernel))
    adj = _tuple2(a.get("adj"), (0,) * len(kernel))
    attrs = [_attr_ints("kernel_shape", kernel),
             _attr_ints("strides", stride),
             _attr_ints("pads", list(pad) * 2),
             _attr_ints("dilations", dilate),
             _attr_ints("output_padding", adj),
             _attr_i("group", a.get("num_group", 1))]
    return b.add("ConvTranspose", ins, name, attrs=attrs)


@mx_op("FullyConnected")
def _fc(b, name, ins, a):
    data = ins[0]
    if a.get("flatten", True):
        data = b.add("Flatten", [data], b.tmp(name + "_flat"),
                     attrs=[_attr_i("axis", 1)])
    gemm_in = [data] + ins[1:]
    return b.add("Gemm", gemm_in, name,
                 attrs=[_attr_f("alpha", 1.0), _attr_f("beta", 1.0),
                        _attr_i("transB", 1)])


@mx_op("BatchNorm", "BatchNorm_v1")
def _bn(b, name, ins, a):
    ins = list(ins)
    if a.get("fix_gamma", True):
        # mxnet's fix_gamma=True (the default) pins scale to 1; ONNX has
        # no such flag, so emit an explicit ones tensor as the scale input
        gamma = b.params.get(ins[1])
        shape = gamma.shape if gamma is not None else (1,)
        ins[1] = b.const(b.tmp(name + "_gamma1"),
                         _onp.ones(shape, _onp.float32))
    return b.add("BatchNormalization", ins, name,
                 attrs=[_attr_f("epsilon", a.get("eps", 1e-3)),
                        _attr_f("momentum", a.get("momentum", 0.9))])


@mx_op("Pooling", "pooling")
def _pool(b, name, ins, a):
    ptype = a.get("pool_type", "max")
    if a.get("global_pool", False):
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}[ptype]
        return b.add(op, ins, name)
    kernel = tuple(a["kernel"])
    stride = _tuple2(a.get("stride"), (1,) * len(kernel))
    pad = _tuple2(a.get("pad"), (0,) * len(kernel))
    attrs = [_attr_ints("kernel_shape", kernel),
             _attr_ints("strides", stride),
             _attr_ints("pads", list(pad) * 2)]
    if ptype == "avg":
        attrs.append(_attr_i("count_include_pad",
                             0 if a.get("count_include_pad",
                                        True) is False else 1))
        return b.add("AveragePool", ins, name, attrs=attrs)
    return b.add("MaxPool", ins, name, attrs=attrs)


# ------------------------------------------------------------ pointwise
_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}


@mx_op("Activation")
def _act(b, name, ins, a):
    t = a.get("act_type", "relu")
    if t == "gelu":
        # exact-erf gelu decomposition: x * 0.5 * (1 + erf(x/sqrt(2)));
        # constants carry the model dtype — mixed-type Mul/Add is invalid
        # ONNX for fp16/bf16 models
        dt = b.np_dtype
        scaled = b.add("Mul", [ins[0], b.const(b.tmp(name + "_c"),
                                               dt(0.7071067811865476))],
                       b.tmp(name + "_sc"))
        erf = b.add("Erf", [scaled], b.tmp(name + "_erf"))
        one = b.const(b.tmp(name + "_one"), dt(1.0))
        half = b.const(b.tmp(name + "_half"), dt(0.5))
        g = b.add("Add", [erf, one], b.tmp(name + "_p1"))
        g = b.add("Mul", [g, half], b.tmp(name + "_h"))
        return b.add("Mul", [ins[0], g], name)
    if t not in _ACT:
        raise NotImplementedError(
            "ONNX export: Activation act_type %r (supported: %s, gelu)"
            % (t, ", ".join(sorted(_ACT))))
    return b.add(_ACT[t], ins, name)


@mx_op("relu")
def _relu(b, name, ins, a):
    return b.add("Relu", ins, name)


@mx_op("sigmoid")
def _sigmoid(b, name, ins, a):
    return b.add("Sigmoid", ins, name)


@mx_op("tanh")
def _tanh(b, name, ins, a):
    return b.add("Tanh", ins, name)


@mx_op("exp")
def _exp(b, name, ins, a):
    return b.add("Exp", ins, name)


@mx_op("log")
def _log(b, name, ins, a):
    return b.add("Log", ins, name)


@mx_op("sqrt")
def _sqrt(b, name, ins, a):
    return b.add("Sqrt", ins, name)


@mx_op("LeakyReLU")
def _leaky(b, name, ins, a):
    t = a.get("act_type", "leaky")
    if t == "elu":
        return b.add("Elu", ins[:1], name,
                     attrs=[_attr_f("alpha", a.get("slope", 0.25))])
    if t == "prelu":
        return b.add("PRelu", ins[:2], name)
    if t != "leaky":
        raise NotImplementedError(
            "ONNX export: LeakyReLU act_type %r (supported: leaky, elu, "
            "prelu)" % t)
    return b.add("LeakyRelu", ins[:1], name,
                 attrs=[_attr_f("alpha", a.get("slope", 0.25))])


@mx_op("softmax", "SoftmaxActivation")
def _softmax(b, name, ins, a):
    return b.add("Softmax", ins[:1], name,
                 attrs=[_attr_i("axis", a.get("axis", -1))])


@mx_op("log_softmax")
def _log_softmax(b, name, ins, a):
    return b.add("LogSoftmax", ins, name,
                 attrs=[_attr_i("axis", a.get("axis", -1))])


@mx_op("Dropout")
def _dropout(b, name, ins, a):
    ratio = b.const(b.tmp(name + "_ratio"),
                    _onp.asarray(a.get("p", 0.5), _onp.float32))
    return b.add("Dropout", [ins[0], ratio], name)


# ---------------------------------------------------------- structural
@mx_op("Flatten", "flatten")
def _flatten(b, name, ins, a):
    return b.add("Flatten", ins, name, attrs=[_attr_i("axis", 1)])


@mx_op("reshape", "Reshape")
def _reshape(b, name, ins, a):
    shape = b.const(b.tmp(name + "_shape"),
                    _onp.asarray(a["shape"], _onp.int64))
    return b.add("Reshape", [ins[0], shape], name)


@mx_op("transpose")
def _transpose(b, name, ins, a):
    axes = a.get("axes")
    attrs = [_attr_ints("perm", axes)] if axes else []
    return b.add("Transpose", ins, name, attrs=attrs)


@mx_op("expand_dims")
def _expand_dims(b, name, ins, a):
    axes = b.const(b.tmp(name + "_axes"),
                   _onp.asarray([a["axis"]], _onp.int64))
    return b.add("Unsqueeze", [ins[0], axes], name)


@mx_op("squeeze")
def _squeeze(b, name, ins, a):
    ax = a.get("axis")
    extra = []
    if ax is not None:
        ax = [ax] if isinstance(ax, int) else list(ax)
        extra = [b.const(b.tmp(name + "_axes"),
                         _onp.asarray(ax, _onp.int64))]
    return b.add("Squeeze", ins + extra, name)


@mx_op("Concat", "concat")
def _concat(b, name, ins, a):
    return b.add("Concat", ins, name,
                 attrs=[_attr_i("axis", a.get("dim", 1))])


# ------------------------------------------------------------ arithmetic
for _mx, _ox in [("elemwise_add", "Add"), ("broadcast_add", "Add"),
                 ("_plus", "Add"), ("elemwise_sub", "Sub"),
                 ("broadcast_sub", "Sub"), ("elemwise_mul", "Mul"),
                 ("broadcast_mul", "Mul"), ("elemwise_div", "Div"),
                 ("broadcast_div", "Div")]:
    def _bin(b, name, ins, a, _ox=_ox):
        return b.add(_ox, ins, name)
    _CONVERTERS[_mx] = _bin


@mx_op("dot", "batch_dot")
def _dot(b, name, ins, a):
    # MatMul has no transpose flags, and the operand rank isn't known at
    # export time, so an implicit-transpose dot cannot be lowered
    # faithfully — refuse rather than emit silently-wrong numerics
    if a.get("transpose_a") or a.get("transpose_b"):
        raise NotImplementedError(
            "ONNX export: dot/batch_dot with transpose_a/transpose_b is "
            "not supported — transpose the operand explicitly instead")
    return b.add("MatMul", ins, name)

_CONVERTERS["add_n"] = lambda b, name, ins, a: b.add("Sum", ins, name)


@mx_op("Embedding")
def _embedding(b, name, ins, a):
    idx = b.add("Cast", [ins[0]], b.tmp(name + "_cast"),
                attrs=[_attr_i("to", P.DT.INT64)])
    return b.add("Gather", [ins[1], idx], name, attrs=[_attr_i("axis", 0)])


# ---------------------------------------------------------------- driver
def export_model(sym, params, input_shapes, input_dtype="float32",
                 onnx_file_path="model.onnx", verbose=False):
    """Export a symbol + params dict to an ONNX file.

    reference: mx.contrib.onnx.export_model(sym, params, in_shapes,
    in_types, onnx_file_path). `params` maps arg/aux names (NDArray or
    numpy). Returns the file path.
    """
    graph = json.loads(sym.tojson())
    nodes = graph["nodes"]
    heads = [h[0] for h in graph["heads"]]

    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}
    host_params = {k: (v.asnumpy() if hasattr(v, "asnumpy") else
                       _onp.asarray(v)) for k, v in params.items()}

    # normalize input_shapes: dict {name: shape}, list of shapes (zipped
    # with data inputs in graph order — the reference API's form), or one
    # shape tuple for a single-input graph
    data_names = [n["name"] for n in nodes
                  if n["op"] == "null" and n["name"] not in host_params]
    if isinstance(input_shapes, dict):
        shape_of = dict(input_shapes)
    elif (isinstance(input_shapes, (list, tuple)) and input_shapes
          and isinstance(input_shapes[0], (list, tuple))):
        if len(input_shapes) != len(data_names):
            raise ValueError(
                "export_model: %d input shapes for %d data inputs %s"
                % (len(input_shapes), len(data_names), data_names))
        shape_of = dict(zip(data_names, map(tuple, input_shapes)))
    else:
        if len(data_names) != 1:
            raise ValueError(
                "export_model: a single shape tuple needs exactly one "
                "data input, graph has %s" % data_names)
        shape_of = {data_names[0]: tuple(input_shapes or ())}

    b = _Builder(host_params)
    if input_dtype == "bfloat16":
        import ml_dtypes as _ml_dtypes
        b.np_dtype = _ml_dtypes.bfloat16
    else:
        b.np_dtype = _onp.dtype(input_dtype).type
    out_name = {}              # node idx -> onnx value name
    graph_inputs = []

    for i, node in enumerate(nodes):
        op, name = node["op"], node["name"]
        if op == "null":
            out_name[i] = name
            if name in host_params:
                b.const(name, host_params[name])
            else:
                shape = shape_of.get(name)
                vi = P.ValueInfoProto(
                    name=name,
                    type=P.TypeProto(tensor_type=P.TensorTypeProto(
                        elem_type=_DTYPE_MAP[input_dtype],
                        shape=P.TensorShapeProto(dim=[
                            P.Dimension(dim_value=int(d))
                            for d in (shape or ())]))))
                graph_inputs.append(vi)
            continue
        conv = _CONVERTERS.get(op)
        if conv is None:
            raise NotImplementedError(
                "ONNX export: no converter for op %r (supported: %s)"
                % (op, ", ".join(sorted(_CONVERTERS))))
        ins = [out_name[j] for j, _, _ in node["inputs"]]
        out_name[i] = conv(b, name, ins, _parse_attrs(node.get("attrs")))
        if verbose:
            print("onnx export: %s -> %s" % (op, out_name[i]))

    outputs = [P.ValueInfoProto(name=out_name[h],
                                type=P.TypeProto(
                                    tensor_type=P.TensorTypeProto(
                                        elem_type=_DTYPE_MAP[input_dtype])))
               for h in heads]

    g = P.GraphProto(name="mxnet_tpu_exported", node=b.nodes,
                     initializer=b.initializers, input=graph_inputs,
                     output=outputs)
    model = P.ModelProto(ir_version=8, producer_name="mxnet-tpu",
                         producer_version="1.9",
                         opset_import=[P.OperatorSetIdProto(domain="",
                                                            version=_OPSET)],
                         graph=g)
    with open(onnx_file_path, "wb") as f:
        f.write(model.encode())
    return onnx_file_path
