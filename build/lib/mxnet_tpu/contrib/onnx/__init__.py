"""mx.contrib.onnx — ONNX model export/import.

reference: python/mxnet/contrib/onnx/ (mx2onnx export_model, onnx2mx
import_model). The reference rides the `onnx` pip package; this build
serializes the ONNX protobuf subset directly (proto.py), so the
capability has no external dependency. Files are standard opset-13 ONNX:
they load in stock onnx/onnxruntime, and import_model accepts files from
stock exporters over the same op set.
"""
from .mx2onnx import export_model
from .onnx2mx import import_model

__all__ = ["export_model", "import_model"]
