"""Text vocabulary. reference: python/mxnet/contrib/text/vocab.py
(Vocabulary): frequency-sorted indexing with reserved tokens and an
unknown-token slot at index 0."""
from __future__ import annotations

import collections

__all__ = ["Vocabulary"]


class Vocabulary:
    """Maps tokens <-> indices. Index 0 is the unknown token; reserved
    tokens follow; then corpus tokens by descending frequency (ties broken
    alphabetically, like the reference).

    counter: collections.Counter of token frequencies (None -> only the
    unknown + reserved tokens). most_freq_count caps the number of corpus
    tokens kept; min_freq drops rare tokens."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            seen = set(reserved_tokens)
            if len(seen) != len(reserved_tokens) or unknown_token in seen:
                raise ValueError("reserved tokens must be unique and must "
                                 "not contain the unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens)
                                 if reserved_tokens else None)
        self._idx_to_token = [unknown_token] + (
            list(reserved_tokens) if reserved_tokens else [])
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter(counter, most_freq_count, min_freq)

    def _index_counter(self, counter, most_freq_count, min_freq):
        assert isinstance(counter, collections.Counter), \
            "counter must be a collections.Counter"
        # frequency desc, then token asc — the reference's ordering
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        kept = 0
        for token, freq in pairs:
            if freq < min_freq:
                break
            if most_freq_count is not None and kept >= most_freq_count:
                break
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                kept += 1

    def __len__(self):
        return len(self._idx_to_token)

    def __contains__(self, token):
        return token in self._token_to_idx

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index/indices (unknown -> 0).
        reference: vocab.py (Vocabulary.to_indices)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        """Index/indices -> token(s). reference: Vocabulary.to_tokens."""
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("token index %d out of range [0, %d)"
                                 % (i, len(self._idx_to_token)))
            out.append(self._idx_to_token[i])
        return out[0] if single else out
