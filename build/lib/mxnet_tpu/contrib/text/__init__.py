"""`mx.contrib.text` — vocabulary + token-embedding utilities.
reference: python/mxnet/contrib/text/__init__.py."""
from . import embedding  # noqa: F401
from . import utils      # noqa: F401
from . import vocab      # noqa: F401
from .vocab import Vocabulary  # noqa: F401
