"""Token embeddings. reference: python/mxnet/contrib/text/embedding.py —
`TokenEmbedding` base with registered sources (`glove`, `fasttext`),
`CustomEmbedding` for local vector files, `CompositeEmbedding`, and the
`register`/`create`/`get_pretrained_file_names` mechanism.

This environment has no network egress, so GloVe/FastText enumerate their
pretrained file names but load only from a local `embedding_root` that
already holds the files; `CustomEmbedding` is the fully-offline path.
"""
from __future__ import annotations

import io
import logging
import os

import numpy as _np

from ... import ndarray as nd
from .vocab import Vocabulary

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "CustomEmbedding", "CompositeEmbedding",
           "GloVe", "FastText"]

_REGISTRY = {}


def register(klass):
    """reference: embedding.py (register) — lowercased class name."""
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(embedding_name, **kwargs):
    """reference: embedding.py (create)."""
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise KeyError("unknown embedding %r (registered: %s)"
                       % (embedding_name, sorted(_REGISTRY)))
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """reference: embedding.py (get_pretrained_file_names)."""
    if embedding_name is not None:
        return list(_REGISTRY[embedding_name.lower()]
                    .pretrained_file_names)
    return {name: list(k.pretrained_file_names)
            for name, k in _REGISTRY.items()}


class TokenEmbedding:
    """Base token embedding: token -> vector with an unknown fallback.
    reference: embedding.py (_TokenEmbedding)."""

    pretrained_file_names = ()

    def __init__(self, unknown_token="<unk>",
                 init_unknown_vec=None):
        self._unknown_token = unknown_token
        self._init_unknown_vec = init_unknown_vec or (lambda s: _np.zeros(s))
        self._idx_to_token = [unknown_token]
        self._token_to_idx = {unknown_token: 0}
        self._idx_to_vec = None
        self._idx_to_vec_np = None   # host cache: one copy, not per lookup
        self._vec_len = 0

    # -- loading ----------------------------------------------------------
    def _load_embedding_txt(self, path, elem_delim=" ", encoding="utf8"):
        """Parse `token v0 v1 ...` lines (the GloVe/fastText text format).
        reference: embedding.py (_load_embedding)."""
        vectors = []
        loaded_unk = None
        with io.open(path, "r", encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if lineno == 0 and len(parts) == 2 and \
                        parts[0].isdigit() and parts[1].isdigit():
                    continue    # fastText header "count dim"
                token, elems = parts[0], parts[1:]
                if not elems:
                    logging.warning("line %d: token with no vector, skipped",
                                    lineno + 1)
                    continue
                vec = _np.asarray([float(e) for e in elems], _np.float32)
                if self._vec_len == 0:
                    self._vec_len = vec.shape[0]
                elif vec.shape[0] != self._vec_len:
                    logging.warning("line %d: dim %d != %d, skipped",
                                    lineno + 1, vec.shape[0], self._vec_len)
                    continue
                if token == self._unknown_token:
                    # the file ships a trained unknown vector — prefer it
                    # over init_unknown_vec (reference _load_embedding)
                    loaded_unk = vec
                    continue
                if token in self._token_to_idx:
                    continue
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                vectors.append(vec)
        unk = (loaded_unk if loaded_unk is not None else
               self._init_unknown_vec((self._vec_len,))).astype(_np.float32)
        self._idx_to_vec = nd.array(
            _np.vstack([unk[None]] + [v[None] for v in vectors]))

    # -- API --------------------------------------------------------------
    def __len__(self):
        return len(self._idx_to_token)

    def __contains__(self, token):
        return token in self._token_to_idx

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _vecs_np(self):
        if self._idx_to_vec_np is None:
            self._idx_to_vec_np = _np.array(self._idx_to_vec.asnumpy())
        return self._idx_to_vec_np

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Token(s) -> vector(s) NDArray.
        reference: embedding.py (get_vecs_by_tokens)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = []
        for t in toks:
            if t in self._token_to_idx:
                idx.append(self._token_to_idx[t])
            elif lower_case_backup and t.lower() in self._token_to_idx:
                idx.append(self._token_to_idx[t.lower()])
            else:
                idx.append(0)
        vecs = self._vecs_np()[idx]
        out = nd.array(vecs[0] if single else vecs)
        return out

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors of known tokens.
        reference: embedding.py (update_token_vectors)."""
        if isinstance(tokens, str):
            tokens = [tokens]
        arr = _np.array(self._vecs_np())   # asnumpy views are read-only
        newv = new_vectors.asnumpy() if isinstance(new_vectors, nd.NDArray) \
            else _np.asarray(new_vectors)
        newv = newv.reshape(len(tokens), -1)
        for t, v in zip(tokens, newv):
            if t not in self._token_to_idx:
                raise ValueError("token %r is unknown; only known tokens "
                                 "can be updated" % (t,))
            arr[self._token_to_idx[t]] = v
        self._idx_to_vec = nd.array(arr)
        self._idx_to_vec_np = arr

    def __getitem__(self, tokens):
        return self.get_vecs_by_tokens(tokens)


@register
class CustomEmbedding(TokenEmbedding):
    """Embedding from a local `token v0 v1 ...` text file.
    reference: embedding.py (CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", **kwargs):
        super().__init__(**kwargs)
        self._load_embedding_txt(pretrained_file_path, elem_delim, encoding)


class _PretrainedEmbedding(TokenEmbedding):
    """Shared loader for named pretrained sources living under
    embedding_root (no network egress in this environment — files must
    already be on disk)."""

    pretrained_file_names = ()

    def __init__(self, pretrained_file_name, embedding_root=None, **kwargs):
        super().__init__(**kwargs)
        if pretrained_file_name not in self.pretrained_file_names:
            raise KeyError(
                "unknown pretrained file %r for %s (choose from %s)"
                % (pretrained_file_name, type(self).__name__,
                   list(self.pretrained_file_names)))
        root = embedding_root or os.path.join(
            os.path.expanduser("~"), ".mxnet", "embeddings",
            type(self).__name__.lower())
        path = os.path.join(root, pretrained_file_name)
        if not os.path.isfile(path):
            raise FileNotFoundError(
                "%s not found. This build has no network egress: place the "
                "file at that path (reference downloads it from the %s "
                "repository)." % (path, type(self).__name__))
        self._load_embedding_txt(path)


@register
class GloVe(_PretrainedEmbedding):
    """reference: embedding.py (GloVe)."""

    pretrained_file_names = (
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt")


@register
class FastText(_PretrainedEmbedding):
    """reference: embedding.py (FastText)."""

    pretrained_file_names = (
        "wiki.en.vec", "wiki.simple.vec", "crawl-300d-2M.vec")


class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary.
    reference: embedding.py (CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        assert isinstance(vocabulary, Vocabulary)
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        super().__init__(unknown_token=vocabulary.unknown_token)
        self._vocab = vocabulary
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = []
        for emb in token_embeddings:
            parts.append(emb.get_vecs_by_tokens(
                self._idx_to_token).asnumpy())
        mat = _np.concatenate(parts, axis=1)
        self._vec_len = mat.shape[1]
        self._idx_to_vec = nd.array(mat)

    @property
    def vocabulary(self):
        return self._vocab
