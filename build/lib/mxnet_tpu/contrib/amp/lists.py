"""AMP op lists. reference: python/mxnet/contrib/amp/lists/symbol_fp16.py —
allow (run in low precision), deny (force fp32), and widest-type ops.

On TPU the low-precision dtype is bf16 (same exponent range as fp32, so the
fp16 overflow machinery is unnecessary but kept for API parity).
"""

# Matmul/conv-class ops: the MXU wants these in bf16.
TARGET_DTYPE_OPS = [
    "Convolution", "Deconvolution", "FullyConnected", "dot", "batch_dot",
    "RNN",
]

# Numerically sensitive ops pinned to fp32 (reference FP32_FUNCS core set).
FP32_OPS = [
    "softmax", "log_softmax", "SoftmaxOutput", "SoftmaxActivation",
    "BatchNorm", "LayerNorm", "InstanceNorm", "L2Normalization", "norm",
    "exp", "log", "log2", "log10", "log1p", "expm1", "rsqrt", "sqrt",
    "square", "sum", "mean", "prod", "nansum", "nanprod", "cumsum",
    "erf", "erfinv", "gamma", "gammaln", "power", "rcbrt", "cbrt",
    "smooth_l1", "arcsin", "arccos", "arctan", "arcsinh", "arccosh",
    "arctanh", "sin", "cos", "tan", "sinh", "cosh", "tanh",
    "linalg_gemm", "linalg_gemm2", "linalg_potrf", "linalg_syrk",
    "moments", "topk",
]

# Elementwise multi-input ops that should run in the widest input dtype.
WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "broadcast_hypot", "elemwise_add", "elemwise_sub", "elemwise_mul",
    "elemwise_div", "add_n", "concat", "Concat", "stack", "where",
    "maximum", "minimum",
]
