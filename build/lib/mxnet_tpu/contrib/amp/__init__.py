"""Automatic mixed precision. reference: python/mxnet/contrib/amp/amp.py."""
from .amp import (init, init_trainer, scale_loss, unscale, convert_model,
                  LossScaler, list_lp16_ops, list_fp32_ops)

__all__ = ["init", "init_trainer", "scale_loss", "unscale", "convert_model",
           "LossScaler", "list_lp16_ops", "list_fp32_ops"]
