"""Functional ResNet (v1 bottleneck), the TPU-first benchmark model.

The API-compatible Gluon model zoo (`mxnet_tpu.gluon.model_zoo.vision`,
mirroring python/mxnet/gluon/model_zoo/vision/resnet.py in the reference)
remains the user-facing surface; this module is the performance path used by
`bench.py` (BASELINE.md headline: ResNet-50 images/sec/chip):

  * NHWC layout — TPU convolutions want feature-minor;
  * bf16 activations/weights, fp32 BatchNorm statistics;
  * one fused jitted train step (fwd+bwd+SGD) so XLA schedules the whole
    iteration; BN running stats are updated inside the same program.
"""
from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ResNetConfig", "resnet_init", "resnet_forward", "resnet_loss",
           "CONFIGS"]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    layers: tuple = (3, 4, 6, 3)          # resnet50
    channels: tuple = (64, 256, 512, 1024, 2048)
    classes: int = 1000
    dtype: object = jnp.bfloat16
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5


CONFIGS = {
    "resnet50": ResNetConfig(),
    "resnet101": ResNetConfig(layers=(3, 4, 23, 3)),
    "resnet152": ResNetConfig(layers=(3, 8, 36, 3)),
    "resnet_tiny": ResNetConfig(layers=(1, 1), channels=(8, 16, 32),
                                classes=10),
}


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    std = math.sqrt(2.0 / fan_in)
    return (jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)
            * std).astype(dtype)


def _bn_init(c):
    return {"gamma": jnp.ones((c,), jnp.float32),
            "beta": jnp.zeros((c,), jnp.float32),
            "mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def resnet_init(key, cfg: ResNetConfig):
    keys = iter(jax.random.split(key, 1024))
    ch = cfg.channels
    params = {
        "stem": {"conv": _conv_init(next(keys), 7, 7, 3, ch[0], cfg.dtype),
                 "bn": _bn_init(ch[0])},
        "stages": {},
        "fc": {"w": _conv_init(next(keys), 1, 1, ch[-1],
                               cfg.classes, cfg.dtype)[0, 0],
               "b": jnp.zeros((cfg.classes,), cfg.dtype)},
    }
    cin = ch[0]
    for si, n_blocks in enumerate(cfg.layers):
        cout = ch[si + 1]
        mid = cout // 4
        stage = {}
        for bi in range(n_blocks):
            stride = 2 if (bi == 0 and si > 0) else 1
            blk = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid, cfg.dtype),
                "bn1": _bn_init(mid),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid, cfg.dtype),
                "bn2": _bn_init(mid),
                "conv3": _conv_init(next(keys), 1, 1, mid, cout, cfg.dtype),
                "bn3": _bn_init(cout),
            }
            if bi == 0:
                blk["down_conv"] = _conv_init(next(keys), 1, 1, cin, cout,
                                              cfg.dtype)
                blk["down_bn"] = _bn_init(cout)
            stage[str(bi)] = blk
            cin = cout
        params["stages"][str(si)] = stage
    return params


def _conv(x, w, stride=1, padding="SAME"):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, p, cfg, train):
    xf = x.astype(jnp.float32)
    if train:
        mu = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        stats = (mu, var)
    else:
        mu, var = p["mean"], p["var"]
        stats = None
    y = (xf - mu) * lax.rsqrt(var + cfg.bn_eps) * p["gamma"] + p["beta"]
    return y.astype(x.dtype), stats


def _bottleneck(x, blk, cfg, train, stride, stats_out, prefix):
    out, s = _bn(_conv(x, blk["conv1"]), blk["bn1"], cfg, train)
    if train:
        stats_out[prefix + "/bn1"] = s
    out = jax.nn.relu(out)
    out, s = _bn(_conv(out, blk["conv2"], stride), blk["bn2"], cfg, train)
    if train:
        stats_out[prefix + "/bn2"] = s
    out = jax.nn.relu(out)
    out, s = _bn(_conv(out, blk["conv3"]), blk["bn3"], cfg, train)
    if train:
        stats_out[prefix + "/bn3"] = s
    if "down_conv" in blk:
        x, s = _bn(_conv(x, blk["down_conv"], stride), blk["down_bn"],
                   cfg, train)
        if train:
            stats_out[prefix + "/down_bn"] = s
    return jax.nn.relu(out + x)


def resnet_forward(params, images, cfg: ResNetConfig, train=False):
    """images (B,H,W,3) → (logits (B,classes) fp32, batch-stats dict).

    In train mode the returned stats dict maps "stages/si/bi/bnX" →
    (batch_mean, batch_var) for the running-stat EMA update (done by the
    caller, outside the grad)."""
    stats = {}
    x = images.astype(cfg.dtype)
    x, s = _bn(_conv(x, params["stem"]["conv"], 2), params["stem"]["bn"],
               cfg, train)
    if train:
        stats["stem/bn"] = s
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    for si in range(len(cfg.layers)):
        stage = params["stages"][str(si)]
        for bi in range(cfg.layers[si]):
            stride = 2 if (bi == 0 and si > 0) else 1
            x = _bottleneck(x, stage[str(bi)], cfg, train, stride, stats,
                            "stages/%d/%d" % (si, bi))
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    logits = x @ params["fc"]["w"].astype(jnp.float32) + \
        params["fc"]["b"].astype(jnp.float32)
    return logits, stats


def resnet_loss(params, batch, cfg: ResNetConfig):
    """Softmax CE; returns (loss, batch stats) for use with has_aux grad."""
    logits, stats = resnet_forward(params, batch["images"], cfg, train=True)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["labels"][..., None],
                               axis=-1)[..., 0]
    return jnp.mean(nll), stats


def update_running_stats(params, stats, cfg: ResNetConfig):
    """EMA the (mean, var) batch stats captured by resnet_loss back into the
    param tree — functional analog of the reference BatchNorm aux states
    (src/operator/nn/batch_norm.cc moving_mean/moving_var)."""
    m = cfg.bn_momentum
    for key, (mu, var) in stats.items():
        parts = key.split("/")
        node = params
        if parts[0] == "stem":
            node = params["stem"]
            bn = node[parts[1]]
        else:
            node = params["stages"][parts[1]][parts[2]]
            bn = node[parts[3]]
        bn["mean"] = m * bn["mean"] + (1 - m) * mu
        bn["var"] = m * bn["var"] + (1 - m) * var
    return params
