"""BERT encoder, TPU-native (BASELINE.json configs[2]: BERT-base).

The reference served BERT through external GluonNLP built on the fused
attention ops in ``src/operator/contrib/transformer.cc``
(``_contrib_interleaved_matmul_selfatt_qk`` etc.); here the whole encoder is
first-class. Param names (``word_embed``, ``layers/<i>/attn/wq``,
``ffn/w1`` …) match :data:`mxnet_tpu.parallel.sharding.BERT_RULES` so the
same tree shards TP+FSDP on a mesh.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from ..parallel.flash_attention import flash_attention
from .llama import _dense_init

__all__ = ["BertConfig", "bert_init", "bert_forward", "bert_mlm_loss",
           "CONFIGS"]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    max_seq_len: int = 512
    n_types: int = 2
    norm_eps: float = 1e-12
    dtype: object = jnp.bfloat16
    remat: bool = False

    @property
    def head_dim(self):
        return self.dim // self.n_heads


CONFIGS = {
    "bert_base": BertConfig(),
    "bert_large": BertConfig(dim=1024, n_layers=24, n_heads=16,
                             hidden_dim=4096),
    "bert_tiny": BertConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                            hidden_dim=128, max_seq_len=128),
}


def bert_init(key, cfg: BertConfig):
    d = cfg.dim
    keys = jax.random.split(key, cfg.n_layers + 4)
    params = {
        "word_embed": _dense_init(keys[0], (cfg.vocab_size, d), cfg.dtype,
                                  scale=0.02),
        "position_embed": _dense_init(keys[1], (cfg.max_seq_len, d),
                                      cfg.dtype, scale=0.02),
        "token_type_embed": _dense_init(keys[2], (cfg.n_types, d),
                                        cfg.dtype, scale=0.02),
        "embed_norm": {"gamma": jnp.ones((d,), jnp.float32),
                       "beta": jnp.zeros((d,), jnp.float32)},
        "layers": {},
    }
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[i + 3], 6)
        params["layers"][str(i)] = {
            "attn": {
                "wq": _dense_init(lk[0], (d, d), cfg.dtype),
                "wk": _dense_init(lk[1], (d, d), cfg.dtype),
                "wv": _dense_init(lk[2], (d, d), cfg.dtype),
                "wo": _dense_init(lk[3], (d, d), cfg.dtype),
                "bq": jnp.zeros((d,), cfg.dtype),
                "bk": jnp.zeros((d,), cfg.dtype),
                "bv": jnp.zeros((d,), cfg.dtype),
                "bo": jnp.zeros((d,), cfg.dtype),
            },
            "attn_norm": {"gamma": jnp.ones((d,), jnp.float32),
                          "beta": jnp.zeros((d,), jnp.float32)},
            "ffn": {
                "w1": _dense_init(lk[4], (d, cfg.hidden_dim), cfg.dtype),
                "b1": jnp.zeros((cfg.hidden_dim,), cfg.dtype),
                "w2": _dense_init(lk[5], (cfg.hidden_dim, d), cfg.dtype),
                "b2": jnp.zeros((d,), cfg.dtype),
            },
            "ffn_norm": {"gamma": jnp.ones((d,), jnp.float32),
                         "beta": jnp.zeros((d,), jnp.float32)},
        }
    return params


def layer_norm(x, p, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean((xf - mu) ** 2, axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * p["gamma"]
            + p["beta"]).astype(x.dtype)


def _encoder_layer(lp, x, cfg):
    B, S, _ = x.shape
    a = lp["attn"]
    q = (x @ a["wq"] + a["bq"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
    k = (x @ a["wk"] + a["bk"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
    v = (x @ a["wv"] + a["bv"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
    o = flash_attention(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                        v.transpose(0, 2, 1, 3), causal=False)
    o = o.transpose(0, 2, 1, 3).reshape(B, S, -1)
    x = layer_norm(x + (o @ a["wo"] + a["bo"]), lp["attn_norm"], cfg.norm_eps)
    f = lp["ffn"]
    h = jax.nn.gelu(x @ f["w1"] + f["b1"], approximate=True)
    return layer_norm(x + (h @ f["w2"] + f["b2"]), lp["ffn_norm"],
                      cfg.norm_eps)


def bert_forward(params, tokens, cfg: BertConfig, token_types=None):
    """tokens (B,S) int32 → hidden states (B,S,D) in cfg.dtype."""
    B, S = tokens.shape
    x = params["word_embed"][tokens]
    x = x + params["position_embed"][None, :S]
    if token_types is None:
        x = x + params["token_type_embed"][0][None, None]
    else:
        x = x + params["token_type_embed"][token_types]
    x = layer_norm(x, params["embed_norm"], cfg.norm_eps)
    layer = (jax.checkpoint(_encoder_layer, static_argnums=(2,))
             if cfg.remat else _encoder_layer)
    for i in range(cfg.n_layers):
        x = layer(params["layers"][str(i)], x, cfg)
    return x


def bert_mlm_loss(params, batch, cfg: BertConfig):
    """Masked-LM loss with weight-tied decoder (hidden @ word_embed.T).
    batch = {'tokens', 'targets', 'mask'} each (B,S); mask 1 where the
    position is an MLM prediction site."""
    h = bert_forward(params, batch["tokens"], cfg)
    logits = (h @ params["word_embed"].T.astype(h.dtype)).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, batch["targets"][..., None],
                               axis=-1)[..., 0]
    mask = batch["mask"].astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
