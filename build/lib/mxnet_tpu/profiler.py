"""Profiler. reference: python/mxnet/profiler.py over src/profiler/ —
per-op aggregate stats + trace dump, `set_config`/`set_state`/`dumps`.

TPU-native design: two layers.
  * Op-level aggregate table (the `profiler.dumps()` experience): the
    imperative `invoke` and `CachedOp` wrap each call in a scope recording
    host-side dispatch time and call counts. Dispatch is async (XLA owns
    the device timeline), so these numbers mean "host time"; device-side
    truth comes from the second layer.
  * Device traces: `set_state('run')` with `profile_all` starts
    `jax.profiler.start_trace` → TensorBoard XPlane dump (the
    chrome://tracing analog of src/profiler/profiler.cc DumpProfile).
"""
from __future__ import annotations

import json
import threading
import time

__all__ = ["set_config", "set_state", "state", "dumps", "dump", "reset",
           "Scope", "scope", "pause", "resume"]

_lock = threading.Lock()
_config = {"profile_all": False, "profile_symbolic": True,
           "profile_imperative": True, "profile_memory": False,
           "profile_api": True, "filename": "profile.json",
           "aggregate_stats": True}
_state = "stop"
_trace_active = False
_agg = {}   # op name -> [count, total_s, min_s, max_s]


def set_config(**kwargs):
    """reference: profiler.py (set_config)."""
    unknown = set(kwargs) - set(_config) - {"profile_process"}
    if unknown:
        raise ValueError("unknown profiler config keys: %s" % unknown)
    _config.update({k: v for k, v in kwargs.items() if k in _config})


def state():
    return _state


def set_state(state_name="stop", profile_process="worker"):
    """reference: profiler.py (set_state) — 'run' | 'stop'."""
    global _state, _trace_active
    if state_name not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    prev = _state
    _state = state_name
    from .ndarray import ndarray as _nd_mod
    _nd_mod._PROFILE_IMPERATIVE = (state_name == "run"
                                   and _config["profile_imperative"])
    if state_name == "run" and prev != "run":
        if _config["profile_all"]:
            try:
                import jax
                jax.profiler.start_trace("/tmp/mxnet_tpu_trace")
                _trace_active = True
            except Exception:
                _trace_active = False
    elif state_name == "stop" and prev == "run":
        if _trace_active:
            import jax
            jax.profiler.stop_trace()
            _trace_active = False


def pause(profile_process="worker"):
    set_state("stop")


def resume(profile_process="worker"):
    set_state("run")


def is_running():
    return _state == "run"


def record_op(name, seconds):
    """Called by the imperative invoke / CachedOp hooks."""
    with _lock:
        ent = _agg.get(name)
        if ent is None:
            _agg[name] = [1, seconds, seconds, seconds]
        else:
            ent[0] += 1
            ent[1] += seconds
            ent[2] = min(ent[2], seconds)
            ent[3] = max(ent[3], seconds)


def reset():
    with _lock:
        _agg.clear()


def dumps(reset_stats=False, format="table"):
    """Aggregate per-op stats table. reference: profiler.py (dumps) over
    src/profiler/aggregate_stats.cc."""
    with _lock:
        rows = sorted(_agg.items(), key=lambda kv: -kv[1][1])
        if format == "json":
            out = json.dumps({k: {"count": v[0], "total_ms": v[1] * 1e3,
                                  "min_ms": v[2] * 1e3, "max_ms": v[3] * 1e3,
                                  "avg_ms": v[1] / v[0] * 1e3}
                              for k, v in rows})
        else:
            lines = ["%-40s %10s %12s %12s %12s %12s" %
                     ("Name", "Calls", "Total(ms)", "Avg(ms)", "Min(ms)",
                      "Max(ms)")]
            for k, v in rows:
                lines.append("%-40s %10d %12.3f %12.3f %12.3f %12.3f" %
                             (k, v[0], v[1] * 1e3, v[1] / v[0] * 1e3,
                              v[2] * 1e3, v[3] * 1e3))
            out = "\n".join(lines)
        if reset_stats:
            _agg.clear()
    return out


def dump(finished=True, profile_process="worker"):
    """Write the aggregate table to the configured filename."""
    with open(_config["filename"], "w") as f:
        f.write(dumps(format="json"))


class Scope:
    """Named profiling range usable from user code. reference: profiler.py
    (Scope) / MXProfileCreateTask."""

    def __init__(self, name="<unk>", append_mode=True):
        # append_mode accepted for reference API parity; ranges always
        # aggregate into the op table here
        self.name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            record_op("scope:" + self.name, time.perf_counter() - self._t0)
        return False


scope = Scope
