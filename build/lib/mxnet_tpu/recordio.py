"""RecordIO: the reference's binary record format + packing helpers.

TPU-native reimplementation of dmlc RecordIO (reference:
3rdparty/dmlc-core/include/dmlc/recordio.h — magic 0xced7230a framing,
multi-part records for >2^29 payloads) and python/mxnet/recordio.py
(MXRecordIO/MXIndexedRecordIO/IRHeader pack/unpack). Byte-compatible with
`.rec` files produced by the reference's im2rec, so existing datasets load.

A C++ fast-path reader lives in mxnet_tpu/native (used by the data loader
when built); this module is the always-available pure-python implementation.
"""
from __future__ import annotations

import ctypes
import numbers
import os
import struct

import numpy as _np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_KFLAG_BITS = 29
_LENGTH_MASK = (1 << _KFLAG_BITS) - 1


def _encode_lrec(cflag, length):
    return (cflag << _KFLAG_BITS) | length


def _decode_lrec(lrec):
    return (lrec >> _KFLAG_BITS) & 7, lrec & _LENGTH_MASK


class MXRecordIO:
    """Sequential .rec reader/writer.
    reference: python/mxnet/recordio.py (MXRecordIO) over
    dmlc::RecordIOWriter/Reader."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.fid = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.fid = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.fid = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.pid = os.getpid()
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        d.pop("fid", None)
        return d

    def __setstate__(self, d):
        self.__dict__ = d
        self.fid = None
        is_open = d.get("is_open", False)
        self.is_open = False
        if is_open:
            self.open()

    def _check_pid(self, allow_reset=False):
        """Post-fork safety (reference: MXRecordIO._check_pid)."""
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
            else:
                raise RuntimeError("Forbidden operation in forked process")

    def close(self):
        if not self.is_open:
            return
        self.fid.close()
        self.is_open = False
        self.pid = None

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        """Append one record. reference: dmlc::RecordIOWriter::WriteRecord."""
        assert self.writable
        self._check_pid(allow_reset=False)
        n = len(buf)
        # single-part record (cflag 0); multipart for giant payloads
        if n <= _LENGTH_MASK:
            self.fid.write(struct.pack("<II", _MAGIC, _encode_lrec(0, n)))
            self.fid.write(buf)
            pad = (4 - n % 4) % 4
            if pad:
                self.fid.write(b"\x00" * pad)
        else:
            nparts = (n + _LENGTH_MASK - 1) // _LENGTH_MASK
            off = 0
            for i in range(nparts):
                part = buf[off:off + _LENGTH_MASK]
                off += len(part)
                cflag = 1 if i == 0 else (2 if i < nparts - 1 else 3)
                self.fid.write(struct.pack("<II", _MAGIC,
                                           _encode_lrec(cflag, len(part))))
                self.fid.write(part)
                pad = (4 - len(part) % 4) % 4
                if pad:
                    self.fid.write(b"\x00" * pad)

    def read(self):
        """Read next record or None at EOF.
        reference: dmlc::RecordIOReader::NextRecord."""
        assert not self.writable
        self._check_pid(allow_reset=True)
        parts = []
        while True:
            header = self.fid.read(8)
            if len(header) < 8:
                return None if not parts else b"".join(parts)
            magic, lrec = struct.unpack("<II", header)
            if magic != _MAGIC:
                raise IOError("Invalid RecordIO magic number")
            cflag, length = _decode_lrec(lrec)
            data = self.fid.read(length)
            pad = (4 - length % 4) % 4
            if pad:
                self.fid.read(pad)
            if cflag == 0:
                return data
            parts.append(data)
            if cflag == 3:
                return b"".join(parts)

    def tell(self):
        return self.fid.tell()

    def seek(self, pos):
        assert not self.writable
        self.fid.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """.rec + .idx random access.
    reference: python/mxnet/recordio.py (MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r":
            if os.path.isfile(self.idx_path):
                with open(self.idx_path) as fin:
                    for line in fin.readlines():
                        line = line.strip().split("\t")
                        key = self.key_type(line[0])
                        self.idx[key] = int(line[1])
                        self.keys.append(key)
            else:
                self.rebuild_index()
            self.fidx = None
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    # .rec files up to this size are indexed by the native whole-buffer
    # scanner; larger ones stream header-by-header to bound memory
    _NATIVE_INDEX_MAX_BYTES = 1 << 30

    def rebuild_index(self, write=False):
        """Scan the .rec and regenerate the key→offset index (the reference
        requires a pre-built .idx; here a missing index is recovered by the
        native framing scanner, with a streaming python fallback). Keys are
        the record ordinals. write=True also persists the .idx file."""
        from . import native
        size = os.path.getsize(self.uri)
        starts = None
        if size <= self._NATIVE_INDEX_MAX_BYTES and native.available():
            with open(self.uri, "rb") as f:
                indexed = native.index_recordio_buffer(f.read())
            if indexed is not None:
                starts = indexed[0].tolist()
        if starts is None:
            # streaming scan: headers only, payloads seeked over — bounded
            # memory for arbitrarily large files. Same logical-record and
            # truncated-tail semantics as the native scanner.
            starts = []
            pend_start = None
            with open(self.uri, "rb") as f:
                pos = 0
                while pos + 8 <= size:
                    magic, lrec = struct.unpack("<II", f.read(8))
                    if magic != _MAGIC:
                        raise IOError("Invalid RecordIO magic number")
                    cflag, length = _decode_lrec(lrec)
                    if pos + 8 + length > size:
                        break          # truncated tail: drop cleanly
                    if cflag == 0:
                        starts.append(pos)
                    elif cflag == 1:
                        pend_start = pos
                    elif cflag == 3 and pend_start is not None:
                        starts.append(pend_start)
                        pend_start = None
                    pos += 8 + length + ((4 - length % 4) % 4)
                    f.seek(pos)
        self.idx = {}
        self.keys = []
        for i, s in enumerate(starts):
            key = self.key_type(i)
            self.idx[key] = int(s)
            self.keys.append(key)
        if write:
            with open(self.idx_path, "w") as fout:
                for key in self.keys:
                    fout.write("%s\t%d\n" % (str(key), self.idx[key]))

    def close(self):
        if not self.is_open:
            return
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def seek(self, idx):
        """Seek to the record with key `idx`."""
        assert not self.writable
        self._check_pid(allow_reset=True)
        pos = self.idx[idx]
        self.fid.seek(pos)

    def read_idx(self, idx):
        """reference: MXIndexedRecordIO.read_idx."""
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        """reference: MXIndexedRecordIO.write_idx."""
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IndexedRecordIO = MXIndexedRecordIO


class IRHeader:
    """Image record header. reference: python/mxnet/recordio.py (IRHeader:
    flag, label, id, id2)."""

    __slots__ = ("flag", "label", "id", "id2")
    _FMT = "<IfQQ"

    def __init__(self, flag, label, id_, id2):
        self.flag = flag
        self.label = label
        self.id = id_
        self.id2 = id2


def pack(header, s):
    """Pack a header + byte payload into a record string.
    reference: recordio.py (pack)."""
    flag = header.flag
    label = header.label
    if isinstance(label, (numbers.Number,)):
        hdr = struct.pack(IRHeader._FMT, 0, float(label), header.id,
                          header.id2)
        return hdr + s
    label = _np.asarray(label, dtype=_np.float32)
    hdr = struct.pack(IRHeader._FMT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Unpack a record into (IRHeader, payload).
    reference: recordio.py (unpack)."""
    hdr_size = struct.calcsize(IRHeader._FMT)
    flag, label, id_, id2 = struct.unpack(IRHeader._FMT, s[:hdr_size])
    s = s[hdr_size:]
    if flag > 0:
        label = _np.frombuffer(s[:flag * 4], dtype=_np.float32)
        s = s[flag * 4:]
    header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array. Without OpenCV in this environment, raw numpy
    (.npy) encoding is used for new files; JPEG payloads from existing .rec
    files are still readable wherever a decoder is available (see
    image.imdecode). reference: recordio.py (pack_img)."""
    import io
    buf = io.BytesIO()
    _np.save(buf, img, allow_pickle=False)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, image array).
    reference: recordio.py (unpack_img)."""
    header, s = unpack(s)
    from .image import imdecode
    img = imdecode(s, to_ndarray=False)
    return header, img
