"""Binary serialization of NDArray dicts — the `.params` checkpoint format.

TPU-native analog of the reference's dmlc-serialized save/load (reference:
src/ndarray/ndarray.cc (NDArray::Save/Load), src/c_api/c_api.cc
(MXNDArraySave/MXNDArrayLoad); format constants from include/mxnet/ndarray.h).

Layout (little-endian), following the reference's 1.x on-disk framing:
  uint64 kMXAPINDArrayListMagic (0x112)
  uint64 reserved (0)
  uint64 num_arrays
  per array (NDArray::Save V2):
    uint32 NDARRAY_V2_MAGIC (0xF993FAC9)
    int32  stype (0=default; sparse saved densified, like gluon Parameter._reduce)
    uint32 ndim, int64 dims[ndim]
    int32  dev_type, int32 dev_id        (context; ignored on load)
    int32  dtype (mshadow type code)
    raw data bytes (shape.prod * dtype size)
  uint64 num_names
  per name: uint64 len, bytes

NOTE: the reference mount was empty at survey time (SURVEY.md §0); magic
values follow upstream Apache MXNet 1.x and should be spot-checked against a
real `.params` file when one is available.
"""
from __future__ import annotations

import struct

import numpy as _np

from ..base import _DTYPE_NP_TO_MX, _DTYPE_MX_TO_NP

_LIST_MAGIC = 0x112
_NDARRAY_V2_MAGIC = 0xF993FAC9


def _write_ndarray(f, arr):
    a = _np.ascontiguousarray(arr.asnumpy() if hasattr(arr, "asnumpy")
                              else _np.asarray(arr))
    f.write(struct.pack("<I", _NDARRAY_V2_MAGIC))
    f.write(struct.pack("<i", 0))                       # stype: dense
    f.write(struct.pack("<I", a.ndim))
    for d in a.shape:
        f.write(struct.pack("<q", d))
    f.write(struct.pack("<ii", 1, 0))                   # cpu(0)
    f.write(struct.pack("<i", _DTYPE_NP_TO_MX[_np.dtype(a.dtype)]))
    f.write(a.tobytes())


def _read_ndarray(f):
    magic, = struct.unpack("<I", f.read(4))
    if magic != _NDARRAY_V2_MAGIC:
        raise IOError("bad NDArray magic 0x%x (expected 0x%x)" %
                      (magic, _NDARRAY_V2_MAGIC))
    stype, = struct.unpack("<i", f.read(4))
    ndim, = struct.unpack("<I", f.read(4))
    shape = struct.unpack("<%dq" % ndim, f.read(8 * ndim)) if ndim else ()
    struct.unpack("<ii", f.read(8))                     # context, ignored
    dtype_code, = struct.unpack("<i", f.read(4))
    dt = _DTYPE_MX_TO_NP[dtype_code]
    n = 1
    for d in shape:
        n *= d
    buf = f.read(n * dt.itemsize)
    return _np.frombuffer(buf, dtype=dt).reshape(shape).copy()


def save_ndarrays(fname, data):
    """reference: mx.nd.save — accepts a dict[str, NDArray], list, or single."""
    from ..ndarray.ndarray import NDArray
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _write_ndarray(f, a)
        f.write(struct.pack("<Q", len(names)))
        for nm in names:
            b = nm.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load_ndarrays(fname, ctx=None):
    """reference: mx.nd.load — returns dict if names present, else list."""
    from ..ndarray.ndarray import array
    with open(fname, "rb") as f:
        magic, _ = struct.unpack("<QQ", f.read(16))
        if magic != _LIST_MAGIC:
            raise IOError("bad .params magic 0x%x" % magic)
        n, = struct.unpack("<Q", f.read(8))
        arrays = [_read_ndarray(f) for _ in range(n)]
        n_names, = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(n_names):
            ln, = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode("utf-8"))
    nds = [array(a, ctx=ctx, dtype=a.dtype) for a in arrays]
    if names:
        return dict(zip(names, nds))
    return nds
