"""Data iterators.

TPU-native analog of the reference's `mx.io` (reference: python/mxnet/io/io.py
(DataIter, NDArrayIter, DataBatch, DataDesc), src/io/iter_prefetcher.h).
The C++ PrefetcherIter double-buffering maps to async PjRt H2D transfers:
`as_in_context` on a jax backend is non-blocking, so handing the next batch to
the device while the current one computes happens naturally.
"""
from __future__ import annotations

from collections import namedtuple

import numpy as _np

from ..ndarray.ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "LibSVMIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """reference: python/mxnet/io/io.py (DataDesc)."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), _np.dtype(dtype), layout)


class DataBatch:
    """reference: python/mxnet/io/io.py (DataBatch)."""

    def __init__(self, data, label=None, pad=0, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """reference DataIter protocol: reset / next / iter_next / getdata."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _init_data(data, allow_empty, default_name):
    if data is None:
        return []
    if isinstance(data, (NDArray, _np.ndarray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {("%s_%d" % (default_name, i)) if len(data) > 1 else
                default_name: d for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, _np.ndarray):
            v = array(v, dtype=v.dtype if v.dtype != _np.float64 else None)
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """reference: python/mxnet/io/io.py (NDArrayIter) — iterate over in-memory
    arrays with optional shuffle and last-batch padding/discard."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 shuffle_seed=None,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        self.last_batch_handle = last_batch_handle
        self.shuffle = shuffle
        self._shuffle_seed = shuffle_seed
        self.cursor = -batch_size
        self._order = _np.arange(self.num_data)
        if shuffle:
            self._rng = _np.random.RandomState(shuffle_seed)
            self._rng.shuffle(self._order)
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            self._rng.shuffle(self._order)

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, arrs):
        out = []
        for _, v in arrs:
            idx = self._order[self.cursor:self.cursor + self.batch_size]
            if len(idx) < self.batch_size and self.last_batch_handle == "pad":
                wrap = self._order[:self.batch_size - len(idx)]
                idx = _np.concatenate([idx, wrap])
            out.append(v[array(idx, dtype="int32")]
                       if isinstance(v, NDArray) else array(v[idx]))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class ResizeIter(DataIter):
    """reference: io.py (ResizeIter) — resize an iterator to n batches/epoch."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    @property
    def provide_data(self):
        return self.data_iter.provide_data

    @property
    def provide_label(self):
        return self.data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration


class PrefetchingIter(DataIter):
    """reference: io.py (PrefetchingIter) — background-thread prefetch
    (the C++ PrefetcherIter analog; device H2D is already async under PjRt)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        import queue
        import threading
        if not isinstance(iters, list):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self._queue = queue.Queue(maxsize=2)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    @property
    def provide_data(self):
        return sum([i.provide_data for i in self.iters], [])

    @property
    def provide_label(self):
        return sum([i.provide_label for i in self.iters], [])

    def _start(self):
        import threading

        def worker():
            try:
                while not self._stop.is_set():
                    batches = [i.next() for i in self.iters]
                    self._queue.put(batches)
            except StopIteration:
                self._queue.put(None)

        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except Exception:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        for i in self.iters:
            i.reset()
        self._stop.clear()
        self._start()

    def next(self):
        batches = self._queue.get()
        if batches is None:
            raise StopIteration
        b = batches[0]
        if len(batches) > 1:
            data = sum([x.data for x in batches], [])
            label = sum([x.label for x in batches], [])
            return DataBatch(data=data, label=label, pad=b.pad)
        return b

    def iter_next(self):
        raise NotImplementedError


class LibSVMIter(DataIter):
    """LibSVM-format iterator yielding CSR data batches. reference:
    src/io/iter_libsvm.cc (LibSVMIter) — the input path of the sparse
    linear/FM configs (BASELINE config #4). Format per line:
    ``label idx:val idx:val ...`` (indices may be 0- or 1-based; pass
    the feature dim via data_shape)."""

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=None, round_batch=True,
                 **kwargs):
        super().__init__(batch_size)
        self._data_shape = (data_shape,) if isinstance(data_shape, int) \
            else tuple(data_shape)
        dim = self._data_shape[-1]
        labels, rows_data, rows_idx = [], [], []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                idx, val = [], []
                for tok in parts[1:]:
                    i, v = tok.split(":")
                    idx.append(int(i))
                    val.append(float(v))
                rows_idx.append(idx)
                rows_data.append(val)
        if label_libsvm is not None:
            # separate label file (reference: iter_libsvm.cc label_libsvm) —
            # first token per line is the label; feature tokens are ignored
            labels = []
            with open(label_libsvm) as f:
                for line in f:
                    parts = line.split()
                    if parts:
                        labels.append(float(parts[0]))
            if len(labels) != len(rows_data):
                raise ValueError(
                    "label_libsvm has %d rows but data has %d"
                    % (len(labels), len(rows_data)))
        self._num = len(labels)
        self._labels = _np.asarray(labels, dtype=_np.float32)
        self._rows_idx = rows_idx
        self._rows_data = rows_data
        self._dim = dim
        self.cursor = -batch_size
        self.round_batch = round_batch
        self.num_batches = (self._num + batch_size - 1) // batch_size

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._dim),
                         _np.float32)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,), _np.float32)]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self._num

    def next(self):
        if not self.iter_next():
            raise StopIteration
        from ..ndarray import sparse as _sp
        start = self.cursor
        stop = min(start + self.batch_size, self._num)
        sel = list(range(start, stop))
        pad = self.batch_size - len(sel)
        if pad and self.round_batch:
            # wrap around (reference round_batch); modulo handles datasets
            # smaller than one batch
            sel += [i % self._num for i in range(pad)]
        data_vals, col_idx, indptr = [], [], [0]
        for i in sel:
            data_vals.extend(self._rows_data[i])
            col_idx.extend(self._rows_idx[i])
            indptr.append(len(col_idx))
        csr = _sp.csr_matrix(
            (_np.asarray(data_vals, _np.float32),
             _np.asarray(col_idx, _np.int32),
             _np.asarray(indptr, _np.int32)),
            shape=(len(sel), self._dim))
        label = array(self._labels[sel])
        # pad counts wrap rows so consumers (BaseModule.predict) can slice
        # them off — same contract as NDArrayIter.getpad()
        return DataBatch(data=[csr], label=[label],
                         pad=pad if self.round_batch else 0)
