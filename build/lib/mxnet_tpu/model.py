"""Checkpoint helpers + BatchEndParam.
reference: python/mxnet/model.py (save_checkpoint/load_checkpoint,
BatchEndParam). The FeedForward class of the reference is deprecated there;
`mx.mod.Module` is the supported path (provided in mxnet_tpu/module/).
"""
from __future__ import annotations

from collections import namedtuple

from . import ndarray as nd
from . import symbol as sym

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint",
           "load_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save `prefix-symbol.json` + `prefix-%04d.params`.
    reference: model.py (save_checkpoint)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix, remove_amp_cast=remove_amp_cast)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)


def load_params(prefix, epoch):
    """reference: model.py (load_params)."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        if tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Returns (symbol, arg_params, aux_params).
    reference: model.py (load_checkpoint)."""
    symbol = sym.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
