"""Runtime feature detection. reference: python/mxnet/runtime.py
(`Features`, `feature_list`) over src/libinfo.cc (MXLibInfoFeatures) —
build-time flags surfaced at runtime. Here features are discovered live
from the JAX/PjRt environment.
"""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list", "is_enabled"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return "[%s: %s]" % ("✔" if self.enabled else "✖", self.name)


def _detect():
    import jax

    feats = {}
    platforms = set()
    try:
        for d in jax.devices():
            platforms.add(d.platform)
    except RuntimeError:
        pass
    feats["TPU"] = bool(platforms & {"tpu", "axon"})
    feats["CPU"] = True
    feats["CUDA"] = "gpu" in platforms or "cuda" in platforms
    # the reference's vendor-kernel flags map to the XLA stack
    feats["CUDNN"] = False
    feats["MKLDNN"] = False
    feats["XLA"] = True
    try:
        from jax.experimental import pallas  # noqa: F401
        feats["PALLAS"] = True
    except ImportError:
        feats["PALLAS"] = False
    feats["BF16"] = True
    feats["INT64_TENSOR_SIZE"] = True
    feats["SIGNAL_HANDLER"] = False
    feats["PROFILER"] = True
    # multi-controller distributed (the dist_kvstore analog)
    feats["DIST_KVSTORE"] = True
    feats["OPENMP"] = False
    feats["SSE"] = False
    feats["F16C"] = False
    feats["JEMALLOC"] = False
    feats["OPENCV"] = False
    return feats


class Features(dict):
    """reference: runtime.py (Features) — dict of name → Feature."""

    instance = None

    def __init__(self):
        super().__init__([(k, Feature(k, v)) for k, v in _detect().items()])

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("Feature '%s' is unknown, known features are: "
                               "%s" % (feature_name, list(self.keys())))
        return self[feature_name].enabled


def feature_list():
    """reference: runtime.py (feature_list)."""
    if Features.instance is None:
        Features.instance = Features()
    return list(Features.instance.values())


def is_enabled(feature_name):
    if Features.instance is None:
        Features.instance = Features()
    return Features.instance.is_enabled(feature_name)


def honor_jax_platforms_env():
    """Force jax back onto the platform named by JAX_PLATFORMS.

    The axon sitecustomize re-registers its TPU backend and resets
    jax_platforms AFTER env vars are read, so scripts documented as
    `JAX_PLATFORMS=cpu ... python script.py` would silently ignore the env
    var. Call this before any jax use (examples/ and tools/ do)."""
    import os
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
