"""`mx.monitor` — training introspection.

reference: python/mxnet/monitor.py (Monitor): registers a stat function
over intermediate outputs/weights/gradients each N batches and prints an
aggregate table. The reference hooks the executor's output callback; here
Module calls `tic_print` around forward/backward and the monitor reads the
bound arrays directly (same information, no engine callback needed since
dispatch is async under PjRt anyway).
"""
from __future__ import annotations

import logging
import math
import re

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """reference: monitor.py (Monitor)."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):  # |x|_1 / size — the reference default
                return x.abs().mean()
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        """Track an executor's arrays (reference: Monitor.install)."""
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []

    def toc(self):
        """Collect stats from installed executors; returns (step, name,
        stat) triples (reference: Monitor.toc)."""
        if not self.activated:
            return []
        for exe in self.exes:
            arrays = {}
            arg_names = getattr(exe, "arg_names", None) or []
            arg_arrays = getattr(exe, "arg_arrays", None) or []
            arrays.update(zip(arg_names, arg_arrays))
            grads = getattr(exe, "grad_arrays", None) or []
            arrays.update(("%s_grad" % n, g)
                          for n, g in zip(arg_names, grads) if g is not None)
            outs = getattr(exe, "outputs", None) or []
            arrays.update(("output%d" % i, o) for i, o in enumerate(outs))
            for name, arr in arrays.items():
                if not isinstance(arr, NDArray):
                    continue
                if self.re_pattern.match(name):
                    self.queue.append((self.step, name,
                                       self.stat_func(arr)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for step, name, stat in self.queue:
            val = float(stat.asnumpy().reshape(-1)[0]) \
                if isinstance(stat, NDArray) else float(stat)
            res.append((step, name, val))
        self.step += 1
        self.queue = []
        return res

    def toc_print(self):
        """Collect and log (reference: Monitor.toc_print)."""
        res = self.toc()
        for step, name, value in res:
            logging.info("Batch: %7d %30s %s", step, name,
                         "nan" if math.isnan(value) else "%.8g" % value)
        return res
