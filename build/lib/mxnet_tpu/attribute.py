"""`mx.attribute` — AttrScope for symbol attributes.

reference: python/mxnet/attribute.py (AttrScope): a thread-local `with`
scope whose attrs (e.g. __ctx_group__, lr_mult, wd_mult) are attached to
every symbol created inside.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]

_STATE = threading.local()


def _stack():
    if not hasattr(_STATE, "stack"):
        _STATE.stack = [{}]
    return _STATE.stack


class AttrScope:
    """reference: attribute.py (AttrScope)."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("AttrScope values must be strings")
        self._attrs = kwargs

    def get(self, attrs=None):
        """Merge the active scope into `attrs` (scope first, explicit
        attrs win)."""
        merged = dict(_stack()[-1])
        if attrs:
            merged.update(attrs)
        return merged

    def __enter__(self):
        merged = dict(_stack()[-1])
        merged.update(self._attrs)
        _stack().append(merged)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


def current():
    """The active attribute dict."""
    return dict(_stack()[-1])
