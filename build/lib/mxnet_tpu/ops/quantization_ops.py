"""INT8 quantization ops.

reference: src/operator/quantization/ — quantize_v2.cc, dequantize.cc,
requantize.cc, quantized_fully_connected.cc, quantized_conv.cc.

TPU-first design: the MXU consumes int8 pairs natively through XLA's
`dot_general`/`conv_general_dilated` with `preferred_element_type=int32`;
there is no custom GEMM kernel to write. Quantization here is SYMMETRIC
int8 (the scheme the reference uses for int8: zero-point-free, scale =
127/threshold), which keeps the matmul a plain integer dot — affine zero
points would add cross terms the MXU cannot fuse.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

INT8_MAX = 127.0


def _thresh(min_range, max_range):
    """Symmetric threshold from a calibrated (min, max) range."""
    return jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))


@register("_contrib_quantize_v2", arity=1, differentiable=False,
          num_outputs=3)
def quantize_v2(data, min_calib_range=None, max_calib_range=None,
                out_type="int8"):
    """fp32 -> int8 with either calibrated or dynamic (per-tensor) range.
    Returns (quantized, min_range, max_range) like the reference op."""
    if out_type not in ("int8", "auto"):
        raise NotImplementedError("quantize_v2: only int8 out_type")
    if min_calib_range is not None and max_calib_range is not None:
        mn = jnp.float32(min_calib_range)
        mx = jnp.float32(max_calib_range)
    else:
        mx = jnp.max(jnp.abs(data)).astype(jnp.float32)
        mn = -mx
    t = _thresh(mn, mx)
    scale = INT8_MAX / jnp.maximum(t, 1e-30)
    q = jnp.clip(jnp.round(data.astype(jnp.float32) * scale),
                 -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, -t, t


@register("_contrib_dequantize", arity=3, differentiable=False)
def dequantize(data, min_range, max_range, out_type="float32"):
    t = _thresh(min_range, max_range)
    # int8 payloads map [-127,127] -> [-t,t]; int32 accumulators from the
    # quantized matmul/conv ops carry the product scale (127*127 per unit)
    denom = INT8_MAX if data.dtype == jnp.int8 else INT8_MAX * INT8_MAX
    return data.astype(jnp.float32) * (t / denom)


@register("_contrib_requantize", arity=3, differentiable=False,
          num_outputs=3)
def requantize(data, min_range, max_range, min_calib_range=None,
               max_calib_range=None):
    """int32 accumulator -> int8 given the accumulator's real-valued range.
    reference: requantize.cc."""
    t_in = _thresh(min_range, max_range)
    real = data.astype(jnp.float32) * (t_in / (INT8_MAX * INT8_MAX))
    if min_calib_range is not None and max_calib_range is not None:
        t_out = _thresh(jnp.float32(min_calib_range),
                        jnp.float32(max_calib_range))
    else:
        t_out = jnp.max(jnp.abs(real))
    scale = INT8_MAX / jnp.maximum(t_out, 1e-30)
    q = jnp.clip(jnp.round(real * scale), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), -t_out, t_out


@register("_contrib_quantized_fully_connected", arity=9,
          differentiable=False, num_outputs=3)
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias, max_bias,
                              num_hidden=None, no_bias=False, flatten=True):
    """int8 x int8 -> int32 FC. reference: quantized_fully_connected.cc
    (outputs int32 + the range the int32 values represent)."""
    x = data
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    out = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())),
                          preferred_element_type=jnp.int32)
    t_d, t_w = _thresh(min_data, max_data), _thresh(min_weight, max_weight)
    if bias is not None and not no_bias:
        # rescale the int8 bias into the int32 accumulator's scale
        t_b = _thresh(min_bias, max_bias)
        acc_scale = (INT8_MAX * INT8_MAX) / jnp.maximum(t_d * t_w, 1e-30)
        b32 = jnp.round(bias.astype(jnp.float32) * (t_b / INT8_MAX)
                        * acc_scale).astype(jnp.int32)
        out = out + b32
    t_out = t_d * t_w  # value represented by accumulator = v/127^2*t_out
    return out, -t_out, t_out


@register("_contrib_quantized_conv", arity=9, differentiable=False,
          num_outputs=3)
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias, max_bias, kernel=None, stride=None,
                   dilate=None, pad=None, num_filter=None, num_group=1,
                   no_bias=False, layout=None):
    """int8 NCHW conv -> int32. reference: quantized_conv.cc."""
    if layout not in (None, "NCHW"):
        raise NotImplementedError(
            "_contrib_quantized_conv: only NCHW layout (got %r)" % layout)
    nd = len(kernel) if kernel is not None else data.ndim - 2

    def _pair(v, n):
        if v is None:
            v = 1
        if isinstance(v, (tuple, list)):
            return tuple(int(x) for x in v)
        return (int(v),) * n

    stride = _pair(stride if stride else 1, nd)
    dilate = _pair(dilate if dilate else 1, nd)
    pad = _pair(pad if pad else 0, nd)
    spatial = "DHW"[3 - nd:]
    dn = lax.conv_dimension_numbers(
        data.shape, weight.shape,
        ("NC" + spatial, "OI" + spatial, "NC" + spatial))
    out = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    t_d, t_w = _thresh(min_data, max_data), _thresh(min_weight, max_weight)
    if bias is not None and not no_bias:
        t_b = _thresh(min_bias, max_bias)
        acc_scale = (INT8_MAX * INT8_MAX) / jnp.maximum(t_d * t_w, 1e-30)
        b32 = jnp.round(bias.astype(jnp.float32) * (t_b / INT8_MAX)
                        * acc_scale).astype(jnp.int32)
        out = out + b32.reshape((1, -1) + (1,) * nd)
    t_out = t_d * t_w
    return out, -t_out, t_out
