"""Random sampling ops.

TPU-native analog of the reference's src/operator/random/* (reference:
sample_op.cc (_random_uniform, _random_normal, _random_gamma, ...),
multisample_op.cc, shuffle_op.cc, unique_sample_op.cc). Every op consumes a
threefry subkey from the per-context key table (mxnet_tpu.random), preserving
the reference's `mx.random.seed` determinism while staying functional.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias
from ..base import np_dtype


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register("_random_uniform", creation=True, random=True, differentiable=False)
def _random_uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None,
                    key=None):
    return jax.random.uniform(key, _shape(shape), dtype=np_dtype(dtype),
                              minval=low, maxval=high)


@register("_random_normal", creation=True, random=True, differentiable=False)
def _random_normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None,
                   key=None):
    # the reference kernel CHECKs sigma >= 0 (sample_op.h); raising inside
    # the op makes this the canonical deferred-async-error test vector
    # (test_exc_handling.py: error surfaces at asnumpy, not at dispatch)
    if not isinstance(scale, jax.core.Tracer) and float(scale) < 0:
        raise ValueError("normal: scale (sigma) must be non-negative, "
                         "got %s" % scale)
    return loc + scale * jax.random.normal(key, _shape(shape),
                                           dtype=np_dtype(dtype))


@register("_random_randint", creation=True, random=True, differentiable=False)
def _random_randint(low=0, high=None, shape=None, dtype="int32", ctx=None,
                    key=None):
    return jax.random.randint(key, _shape(shape), low, high,
                              dtype=np_dtype(dtype))


@register("_random_gamma", creation=True, random=True, differentiable=False)
def _random_gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None,
                  key=None):
    return beta * jax.random.gamma(key, alpha, _shape(shape),
                                   dtype=np_dtype(dtype))


@register("_random_exponential", creation=True, random=True, differentiable=False)
def _random_exponential(lam=1.0, shape=None, dtype="float32", ctx=None, key=None):
    return jax.random.exponential(key, _shape(shape),
                                  dtype=np_dtype(dtype)) / lam


@register("_random_poisson", creation=True, random=True, differentiable=False)
def _random_poisson(lam=1.0, shape=None, dtype="float32", ctx=None, key=None):
    return jax.random.poisson(key, lam, _shape(shape)).astype(np_dtype(dtype))


@register("_random_negative_binomial", creation=True, random=True,
          differentiable=False)
def _random_negative_binomial(k=1, p=1.0, shape=None, dtype="float32",
                              ctx=None, key=None):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


@register("_random_generalized_negative_binomial", creation=True, random=True,
          differentiable=False)
def _random_gen_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", ctx=None, key=None):
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam).astype(np_dtype(dtype))


@register("_sample_unique_zipfian", creation=True, random=True,
          differentiable=False)
def _sample_unique_zipfian(range_max=1, shape=None, ctx=None, key=None):
    # log-uniform (zipfian) sampling used by sampled-softmax candidate sampling
    u = jax.random.uniform(key, _shape(shape))
    s = jnp.exp(u * jnp.log(float(range_max))).astype(jnp.int64) - 1
    return jnp.clip(s, 0, range_max - 1)


# sample_* variants: per-element distribution parameters as array inputs
@register("_sample_uniform", random=True, differentiable=False)
def _sample_uniform(low, high, shape=None, dtype="float32", key=None):
    sh = _shape(shape)
    out_shape = low.shape + sh
    u = jax.random.uniform(key, out_shape, dtype=np_dtype(dtype))
    return low.reshape(low.shape + (1,) * len(sh)) + u * (
        (high - low).reshape(low.shape + (1,) * len(sh)))


@register("_sample_normal", random=True, differentiable=False)
def _sample_normal(mu, sigma, shape=None, dtype="float32", key=None):
    sh = _shape(shape)
    out_shape = mu.shape + sh
    z = jax.random.normal(key, out_shape, dtype=np_dtype(dtype))
    return mu.reshape(mu.shape + (1,) * len(sh)) + z * sigma.reshape(
        sigma.shape + (1,) * len(sh))


@register("_sample_gamma", random=True, differentiable=False)
def _sample_gamma(alpha, beta, shape=None, dtype="float32", key=None):
    sh = _shape(shape)
    a = alpha.reshape(alpha.shape + (1,) * len(sh))
    g = jax.random.gamma(key, jnp.broadcast_to(a, alpha.shape + sh),
                         dtype=np_dtype(dtype))
    return g * beta.reshape(beta.shape + (1,) * len(sh))


@register("_sample_multinomial", random=True, differentiable=False)
def _sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                        key=None):
    """reference: multisample_op.cc (_sample_multinomial) — `data` is a
    (batch of) probability vector(s)."""
    sh = _shape(shape)
    n = 1
    for s in sh:
        n *= s
    logits = jnp.log(jnp.maximum(data, 1e-38))
    if data.ndim == 1:
        draws = jax.random.categorical(key, logits, shape=(n,))
        out = draws.reshape(sh) if sh else draws[0]
    else:
        draws = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                       shape=(data.shape[0], n))
        out = draws.reshape((data.shape[0],) + sh) if sh else draws[:, 0]
    out = out.astype(np_dtype(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1),
            out.astype(jnp.int32).reshape(data.shape[0], -1) if data.ndim > 1
            else out.astype(jnp.int32).reshape(1, -1), axis=-1)
        return out, lp.reshape(out.shape)
    return out


@register("_shuffle", random=True, differentiable=False)
def _shuffle(data, key=None):
    """reference: shuffle_op.cc — permutes along the first axis."""
    return jax.random.permutation(key, data, axis=0)


@register("bernoulli", random=True, differentiable=False)
def _bernoulli(data, key=None):
    return jax.random.bernoulli(key, data).astype(jnp.float32)


alias("_random_uniform", "uniform", "random_uniform")
alias("_random_normal", "normal", "random_normal", "randn")
alias("_random_randint", "randint", "random_randint")
alias("_random_gamma", "random_gamma")
alias("_random_exponential", "random_exponential")
alias("_random_poisson", "random_poisson")
alias("_random_negative_binomial", "random_negative_binomial")
alias("_random_generalized_negative_binomial",
      "random_generalized_negative_binomial")
alias("_sample_multinomial", "sample_multinomial")
alias("_shuffle", "shuffle")
