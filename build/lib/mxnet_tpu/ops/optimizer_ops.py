"""Optimizer update ops.

TPU-native analog of reference src/operator/optimizer_op.cc (sgd_update,
sgd_mom_update, adam_update, mp_* multi-precision variants, ...). Each op is
a pure function over jax arrays returning the updated tensors; the imperative
`out=` / in-place write convention of the reference is provided by the
NDArray invoke layer. Under a jitted trainer step these all fuse into the
surrounding graph (the reference needed hand-fused CUDA kernels; XLA does it).

All follow the reference's update rules exactly, including the order of
weight-decay/momentum application and `rescale_grad`/`clip_gradient`
preprocessing.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import registry as _reg
from .registry import register, alias


def _prep_grad(grad, rescale_grad, clip_gradient):
    grad = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    return grad


@register("sgd_update", arity=2, differentiable=False)
def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=True):
    """reference: src/operator/optimizer_op.cc (sgd_update)."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (grad + wd * weight)


@register("sgd_mom_update", arity=3, differentiable=False, num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    """reference: sgd_mom_update — mom = momentum*mom - lr*(grad + wd*w);
    w += mom."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    mom = momentum * mom - lr * (grad + wd * weight)
    return weight + mom, mom


@register("mp_sgd_update", arity=3, differentiable=False, num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=True):
    """fp16 weights with fp32 master copy (reference: mp_sgd_update)."""
    grad32 = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    w32 = weight32 - lr * (grad32 + wd * weight32)
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", arity=4, differentiable=False, num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0, lazy_update=True):
    grad32 = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    mom = momentum * mom - lr * (grad32 + wd * weight32)
    w32 = weight32 + mom
    return w32.astype(weight.dtype), mom, w32


@register("nag_mom_update", arity=3, differentiable=False, num_outputs=2)
def nag_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """Nesterov momentum (reference: nag_mom_update)."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    grad = grad + wd * weight
    mom = momentum * mom + grad
    return weight - lr * (grad + momentum * mom), mom


@register("mp_nag_mom_update", arity=4, differentiable=False, num_outputs=3)
def mp_nag_mom_update(weight, grad, mom, weight32, lr, momentum=0.0, wd=0.0,
                      rescale_grad=1.0, clip_gradient=-1.0):
    grad32 = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient)
    grad32 = grad32 + wd * weight32
    mom = momentum * mom + grad32
    w32 = weight32 - lr * (grad32 + momentum * mom)
    return w32.astype(weight.dtype), mom, w32


@register("adam_update", arity=4, differentiable=False, num_outputs=3)
def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=True):
    """reference: adam_update. Bias correction is folded into lr by the
    python Optimizer (as in the reference)."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    mean = beta1 * mean + (1.0 - beta1) * grad
    var = beta2 * var + (1.0 - beta2) * grad * grad
    return weight - lr * mean / (jnp.sqrt(var) + epsilon), mean, var


@register("rmsprop_update", arity=3, differentiable=False, num_outputs=2)
def rmsprop_update(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    """reference: rmsprop_update (non-centered)."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    n = (1.0 - gamma1) * grad * grad + gamma1 * n
    weight = weight - lr * grad / jnp.sqrt(n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        weight = jnp.clip(weight, -clip_weights, clip_weights)
    return weight, n


@register("rmspropalex_update", arity=5, differentiable=False, num_outputs=4)
def rmspropalex_update(weight, grad, n, g, delta, lr, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """reference: rmspropalex_update (centered RMSProp, Graves 2013)."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    n = (1.0 - gamma1) * grad * grad + gamma1 * n
    g = (1.0 - gamma1) * grad + gamma1 * g
    delta = gamma2 * delta - lr * grad / jnp.sqrt(n - g * g + epsilon)
    weight = weight + delta
    if clip_weights is not None and clip_weights > 0:
        weight = jnp.clip(weight, -clip_weights, clip_weights)
    return weight, n, g, delta


@register("ftrl_update", arity=4, differentiable=False, num_outputs=3)
def ftrl_update(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    """reference: ftrl_update."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    new_n = n + grad * grad
    z = z + grad - (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr * weight
    n = new_n
    weight = jnp.where(
        jnp.abs(z) > lamda1,
        -(z - jnp.sign(z) * lamda1) / ((beta + jnp.sqrt(n)) / lr + wd),
        jnp.zeros_like(weight))
    return weight, z, n


@register("signsgd_update", arity=2, differentiable=False)
def signsgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0):
    """reference: signsgd_update."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (jnp.sign(grad) + wd * weight)


@register("signum_update", arity=3, differentiable=False, num_outputs=2)
def signum_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    """reference: signum_update (sign of momentum)."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    mom = momentum * mom - (1 - momentum) * grad
    weight = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom) \
        - lr * wd * weight
    return weight, mom


@register("ftml_update", arity=5, differentiable=False, num_outputs=4)
def ftml_update(weight, grad, d, v, z, lr, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1):
    """reference: ftml_update (FTML, Zheng & Kwok 2017)."""
    grad = _prep_grad(grad, rescale_grad, clip_grad) + wd * weight
    v = beta2 * v + (1 - beta2) * grad * grad
    d_t = (1 - beta1 ** t) / lr * (
        jnp.sqrt(v / (1 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    z = beta1 * z + (1 - beta1) * grad - sigma * weight
    weight = -z / d_t
    return weight, d_t, v, z


@register("adagrad_update", arity=3, differentiable=False, num_outputs=2)
def adagrad_update(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """reference: _sparse_adagrad_update dense path / python AdaGrad."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    history = history + grad * grad
    return weight - lr * (grad / jnp.sqrt(history + epsilon) + wd * weight), \
        history


@register("adadelta_update", arity=4, differentiable=False, num_outputs=3)
def adadelta_update(weight, grad, acc_g, acc_delta, rho=0.9, epsilon=1e-5,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    """python AdaDelta semantics (reference: python/mxnet/optimizer/optimizer.py
    (AdaDelta.update))."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    acc_g = rho * acc_g + (1 - rho) * grad * grad
    delta = jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(acc_g + epsilon) * grad
    acc_delta = rho * acc_delta + (1 - rho) * delta * delta
    return weight - (delta + wd * weight), acc_g, acc_delta


@register("adamax_update", arity=4, differentiable=False, num_outputs=3)
def adamax_update(weight, grad, mean, u, lr, beta1=0.9, beta2=0.999, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0):
    """python Adamax semantics (lr already bias-corrected by caller)."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    mean = beta1 * mean + (1 - beta1) * grad
    u = jnp.maximum(beta2 * u, jnp.abs(grad))
    return weight - lr * mean / u, mean, u


@register("nadam_update", arity=4, differentiable=False, num_outputs=3)
def nadam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0, t=1, m_schedule=1.0):
    """python Nadam semantics. Returns (weight, mean, var); caller tracks
    m_schedule scalar."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    momentum_t = beta1 * (1.0 - 0.5 * 0.96 ** (t * schedule_decay))
    momentum_t_1 = beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * schedule_decay))
    m_schedule_new = m_schedule * momentum_t
    m_schedule_next = m_schedule_new * momentum_t_1
    grad_prime = grad / (1.0 - m_schedule_new)
    mean = beta1 * mean + (1.0 - beta1) * grad
    var = beta2 * var + (1.0 - beta2) * grad * grad
    mean_prime = mean / (1.0 - m_schedule_next)
    var_prime = var / (1.0 - beta2 ** t)
    mean_bar = (1.0 - momentum_t) * grad_prime + momentum_t_1 * mean_prime
    return weight - lr * mean_bar / (jnp.sqrt(var_prime) + epsilon), mean, var


@register("lamb_update_phase1", arity=4, differentiable=False, num_outputs=3)
def lamb_update_phase1(weight, grad, mean, var, beta1=0.9, beta2=0.999,
                       epsilon=1e-6, t=1, bias_correction=True, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0):
    """reference: lamb_update_phase1 — computes the raw update direction g."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    mean = beta1 * mean + (1 - beta1) * grad
    var = beta2 * var + (1 - beta2) * grad * grad
    if bias_correction:
        mean_hat = mean / (1.0 - beta1 ** t)
        var_hat = var / (1.0 - beta2 ** t)
    else:
        mean_hat, var_hat = mean, var
    g = mean_hat / (jnp.sqrt(var_hat) + epsilon) + wd * weight
    return g, mean, var


@register("lamb_update_phase2", arity=4, differentiable=False)
def lamb_update_phase2(weight, g, r1, r2, lr, lower_bound=-1.0,
                       upper_bound=-1.0):
    """reference: lamb_update_phase2 — trust-ratio scaled step."""
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1 > 0, r2 > 0), r1 / r2,
                      jnp.ones_like(r1))
    return weight - lr * ratio * g


@register("adamw_update", arity=4, differentiable=False, num_outputs=3)
def adamw_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    """reference: src/operator/contrib/adamw.cc (_adamw_update) — decoupled
    weight decay."""
    grad = _prep_grad(grad, rescale_grad, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * grad
    var = beta2 * var + (1.0 - beta2) * grad * grad
    weight = weight - eta * (lr * mean / (jnp.sqrt(var) + epsilon)
                             + wd * weight)
    return weight, mean, var


alias("adamw_update", "_adamw_update", "_contrib_adamw_update")
alias("adam_update", "_adam_update")
