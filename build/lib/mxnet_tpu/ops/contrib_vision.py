"""Contrib vision / detection operator pack.

reference: src/operator/contrib/ — `bilinear_resize-inl.h`
(BilinearResize2D), `adaptive_avg_pooling-inl.h` (AdaptiveAvgPooling2D),
`roi_align.cc` (ROIAlign), `bounding_box.cc` (box_nms / box_iou /
box_encode / box_decode), `arange_like-inl.h`. These back the GluonCV
detection/segmentation model family on the reference.

TPU-first notes: everything is static-shape and branch-free so XLA can tile
it — NMS runs a fixed-trip `lax.fori_loop` over score-sorted candidates
with a suppression mask (no dynamic early-exit, which would block
compilation); AdaptiveAvgPooling uses a summed-area table (two cumsums +
four gathers per output cell) instead of data-dependent window loops;
ROIAlign vmaps bilinear sampling over rois.
"""
from __future__ import annotations

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias
from .nn import _pair

__all__ = []


# ---------------------------------------------------------------------------
# arange_like (reference: contrib/arange_like-inl.h)
# ---------------------------------------------------------------------------
@register("_contrib_arange_like", differentiable=False)
def _arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    repeat = max(1, int(repeat))
    if axis is None:
        n = 1
        for d in data.shape:
            n *= d
        idx = jnp.arange(n) // repeat
        return (start + step * idx.astype(data.dtype)).reshape(data.shape)
    n = data.shape[axis]
    idx = jnp.arange(n) // repeat
    return start + step * idx.astype(data.dtype)


# ---------------------------------------------------------------------------
# BilinearResize2D (reference: contrib/bilinear_resize-inl.h) — NCHW,
# align_corners sampling like the reference's kernel
# ---------------------------------------------------------------------------
def _linear_coords(out_size, in_size, dtype):
    if out_size == 1 or in_size == 1:
        src = jnp.zeros((out_size,), dtype)
    else:
        scale = (in_size - 1.0) / (out_size - 1.0)
        src = jnp.arange(out_size, dtype=dtype) * dtype.type(scale) \
            if hasattr(dtype, "type") else jnp.arange(out_size) * scale
        src = jnp.asarray(src, dtype)
    lo = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, in_size - 1)
    hi = jnp.clip(lo + 1, 0, in_size - 1)
    frac = src - lo.astype(src.dtype)
    return lo, hi, frac


@register("_contrib_BilinearResize2D")
def _bilinear_resize2d(data, height=None, width=None, scale_height=None,
                       scale_width=None, mode="size"):
    if mode != "size":
        raise NotImplementedError(
            "BilinearResize2D: mode=%r not supported (only 'size'; the "
            "reference's odd/even/like modes are size policies the caller "
            "can compute and pass as height/width)" % (mode,))
    n, c, h, w = data.shape
    # reference defaults height/width to 1 when neither the size nor the
    # per-axis scale is given
    oh = (int(height) if height else
          int(round(h * float(scale_height))) if scale_height else 1)
    ow = (int(width) if width else
          int(round(w * float(scale_width))) if scale_width else 1)
    f32 = data.astype(jnp.float32)
    ylo, yhi, yf = _linear_coords(oh, h, jnp.float32)
    xlo, xhi, xf = _linear_coords(ow, w, jnp.float32)
    top = f32[:, :, ylo, :] * (1 - yf)[None, None, :, None] + \
        f32[:, :, yhi, :] * yf[None, None, :, None]
    out = top[:, :, :, xlo] * (1 - xf)[None, None, None, :] + \
        top[:, :, :, xhi] * xf[None, None, None, :]
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# AdaptiveAvgPooling2D (reference: contrib/adaptive_avg_pooling-inl.h)
# ---------------------------------------------------------------------------
@register("_contrib_AdaptiveAvgPooling2D")
def _adaptive_avg_pool2d(data, output_size=None):
    n, c, h, w = data.shape
    if output_size is None:
        oh = ow = 1
    elif isinstance(output_size, (tuple, list)):
        oh, ow = (int(output_size[0]),
                  int(output_size[1] if len(output_size) > 1
                      else output_size[0]))
    else:
        oh = ow = int(output_size)
    # summed-area table: S[i, j] = sum(data[:i, :j]); window sums are four
    # gathers — no data-dependent loop bounds, MXU-friendly
    f32 = data.astype(jnp.float32)
    sat = jnp.pad(jnp.cumsum(jnp.cumsum(f32, axis=2), axis=3),
                  ((0, 0), (0, 0), (1, 0), (1, 0)))
    h0 = (_np.arange(oh) * h) // oh
    h1 = -(-(_np.arange(1, oh + 1) * h) // oh)      # ceil
    w0 = (_np.arange(ow) * w) // ow
    w1 = -(-(_np.arange(1, ow + 1) * w) // ow)
    area = ((h1 - h0)[:, None] * (w1 - w0)[None, :]).astype(_np.float32)
    out = (sat[:, :, h1][:, :, :, w1] - sat[:, :, h0][:, :, :, w1]
           - sat[:, :, h1][:, :, :, w0] + sat[:, :, h0][:, :, :, w0])
    return (out / area[None, None]).astype(data.dtype)


# ---------------------------------------------------------------------------
# ROIAlign (reference: contrib/roi_align.cc) — NCHW features, rois
# (R, 5) = [batch_idx, x1, y1, x2, y2] in image coords
# ---------------------------------------------------------------------------
@register("_contrib_ROIAlign", arity=2)
def _roi_align(data, rois, pooled_size=None, spatial_scale=1.0,
               sample_ratio=-1, position_sensitive=False, aligned=False):
    if position_sensitive:
        raise NotImplementedError("ROIAlign: position_sensitive=True")
    ph, pw = (int(pooled_size[0]), int(pooled_size[1])) \
        if isinstance(pooled_size, (tuple, list)) else \
        (int(pooled_size), int(pooled_size))
    s = 2 if sample_ratio is None or sample_ratio <= 0 else int(sample_ratio)
    n, c, h, w = data.shape
    f32 = data.astype(jnp.float32)
    offset = 0.5 if aligned else 0.0

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
        bh, bw = rh / ph, rw / pw
        # sample grid: (ph*s, pw*s) bilinear taps, mean-pooled s×s per cell
        ys = y1 + (jnp.arange(ph * s, dtype=jnp.float32) + 0.5) * (bh / s)
        xs = x1 + (jnp.arange(pw * s, dtype=jnp.float32) + 0.5) * (bw / s)
        # reference roi_align.cc zeroes samples outside [-1, size]; inside
        # that band coordinates clamp to the border for interpolation
        yok = ((ys >= -1.0) & (ys <= h)).astype(jnp.float32)
        xok = ((xs >= -1.0) & (xs <= w)).astype(jnp.float32)
        ysc = jnp.clip(ys, 0, h - 1)
        xsc = jnp.clip(xs, 0, w - 1)
        y0 = jnp.floor(ysc).astype(jnp.int32)
        x0 = jnp.floor(xsc).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        yf = ysc - y0
        xf = xsc - x0
        img = f32[bidx]                                   # (c, h, w)
        top = img[:, y0, :] * (1 - yf)[None, :, None] + \
            img[:, y1i, :] * yf[None, :, None]
        val = top[:, :, x0] * (1 - xf)[None, None, :] + \
            top[:, :, x1i] * xf[None, None, :]            # (c, ph*s, pw*s)
        val = val * (yok[:, None] * xok[None, :])[None]
        val = val.reshape(c, ph, s, pw, s).mean(axis=(2, 4))
        # rois with y2<y1 (empty) produce zeros like the reference
        return val

    out = jax.vmap(one_roi)(rois.astype(jnp.float32))
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# bounding boxes (reference: contrib/bounding_box.cc)
# ---------------------------------------------------------------------------
def _pair_iou(a, b):
    """a: (..., N, 4), b: (..., M, 4) corner boxes -> IoU (..., N, M)."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * \
        jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _to_corner(x):
    xc, yc, w, h = x[..., 0], x[..., 1], x[..., 2], x[..., 3]
    return jnp.stack([xc - w / 2, yc - h / 2, xc + w / 2, yc + h / 2],
                     axis=-1)


@register("_contrib_box_iou", arity=2, differentiable=False)
def _box_iou(lhs, rhs, format="corner"):
    a = lhs.astype(jnp.float32)
    b = rhs.astype(jnp.float32)
    if format == "center":
        a, b = _to_corner(a), _to_corner(b)
    return _pair_iou(a, b)


@register("_contrib_box_nms", differentiable=False)
def _box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
             coord_start=2, score_index=1, id_index=-1, force_suppress=False,
             in_format="corner", out_format="corner", background_id=-1):
    """Score-sorted greedy NMS; suppressed/invalid entries get score -1
    (the reference's convention). Fixed trip count keeps it compilable."""
    if out_format != in_format:
        raise NotImplementedError("box_nms: in/out format conversion")
    squeeze = data.ndim == 2
    if squeeze:
        data = data[None]
    b, n, width = data.shape
    f32 = data.astype(jnp.float32)
    scores = f32[:, :, score_index]
    boxes = lax.dynamic_slice_in_dim(f32, coord_start, 4, axis=2)
    if in_format == "center":
        boxes = _to_corner(boxes)
    ids = (f32[:, :, id_index] if id_index is not None and id_index >= 0
           else jnp.zeros((b, n), jnp.float32))

    valid = scores > valid_thresh
    if id_index is not None and id_index >= 0 and background_id >= 0:
        valid &= ids != background_id
    order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf), axis=1)
    k = n if topk is None or topk <= 0 else min(int(topk), n)

    sb = jnp.take_along_axis(boxes, order[:, :, None], axis=1)
    sv = jnp.take_along_axis(valid, order, axis=1)
    sid = jnp.take_along_axis(ids, order, axis=1)
    iou = _pair_iou(sb, sb)                                # (b, n, n)
    same_cls = (sid[:, :, None] == sid[:, None, :]) | force_suppress

    def body(i, keep):
        # candidate i suppresses every later j overlapping it — only if i
        # itself is still kept
        act = keep[:, i] & sv[:, i]
        sup = (iou[:, i, :] > overlap_thresh) & same_cls[:, i, :] & \
            (jnp.arange(n)[None, :] > i)
        return keep & ~(sup & act[:, None])

    keep = lax.fori_loop(0, k, body, jnp.ones((b, n), bool)) & sv
    keep &= jnp.arange(n)[None, :] < k

    # scatter back to sorted order, score -1 where dropped
    out_sorted = jnp.take_along_axis(f32, order[:, :, None], axis=1)
    new_scores = jnp.where(keep, out_sorted[:, :, score_index], -1.0)
    out_sorted = out_sorted.at[:, :, score_index].set(new_scores)
    out = out_sorted.astype(data.dtype)
    return out[0] if squeeze else out


@register("_contrib_box_encode", arity=6, differentiable=False)
def _box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
                stds=(0.1, 0.1, 0.2, 0.2)):
    """SSD target encoding (reference: bounding_box.cc BoxEncode):
    corner anchors/refs -> normalized center-form offsets."""
    f = jnp.float32
    a = _to_center(anchors.astype(f))
    g = _to_center(jnp.take_along_axis(
        refs.astype(f), matches[..., None].astype(jnp.int32), axis=1))
    t0 = (g[..., 0] - a[..., 0]) / a[..., 2]
    t1 = (g[..., 1] - a[..., 1]) / a[..., 3]
    t2 = jnp.log(jnp.maximum(g[..., 2] / a[..., 2], 1e-12))
    t3 = jnp.log(jnp.maximum(g[..., 3] / a[..., 3], 1e-12))
    t = jnp.stack([t0, t1, t2, t3], axis=-1)
    t = (t - jnp.asarray(means, f)) / jnp.asarray(stds, f)
    mask = (samples[..., None] > 0.5).astype(f)
    return t * mask, mask


def _to_center(x):
    x1, y1, x2, y2 = x[..., 0], x[..., 1], x[..., 2], x[..., 3]
    return jnp.stack([(x1 + x2) / 2, (y1 + y2) / 2,
                      jnp.maximum(x2 - x1, 0.0),
                      jnp.maximum(y2 - y1, 0.0)], axis=-1)


@register("_contrib_box_decode", arity=2, differentiable=False)
def _box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
                clip=-1.0, format="corner"):
    """Inverse of box_encode (reference: bounding_box.cc BoxDecode)."""
    f = jnp.float32
    a = anchors.astype(f)
    if format == "corner":
        a = _to_center(a)
    d = data.astype(f)
    x = d[..., 0] * std0 * a[..., 2] + a[..., 0]
    y = d[..., 1] * std1 * a[..., 3] + a[..., 1]
    dw = d[..., 2] * std2
    dh = d[..., 3] * std3
    if clip is not None and clip > 0:
        dw = jnp.minimum(dw, clip)
        dh = jnp.minimum(dh, clip)
    w = jnp.exp(dw) * a[..., 2] / 2
    h = jnp.exp(dh) * a[..., 3] / 2
    return jnp.stack([x - w, y - h, x + w, y + h],
                     axis=-1).astype(data.dtype)


alias("_contrib_BilinearResize2D", "_contrib_bilinear_resize2d")
alias("_contrib_AdaptiveAvgPooling2D", "_contrib_adaptive_avg_pooling2d")


# ---------------------------------------------------------------------------
# SSD MultiBox ops (reference: contrib/multibox_prior.cc,
# multibox_target.cc, multibox_detection.cc) — the reference's in-tree SSD
# training graph: anchor generation, target matching, decode+NMS.
# ---------------------------------------------------------------------------
@register("_contrib_MultiBoxPrior", differentiable=False)
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchors for one feature map: (1, H*W*A, 4) corner boxes in [0, 1],
    A = len(sizes) + len(ratios) - 1, ordered exactly like the reference
    kernel (multibox_prior-inl.h): every size at the FIRST ratio first,
    then ratios[1:] at sizes[0]. Widths carry the reference's
    in_height/in_width aspect correction so anchors stay square in pixel
    space on non-square feature maps."""
    h, w = data.shape[2], data.shape[3]
    sizes = [float(s) for s in sizes]
    ratios = [float(r) for r in ratios]
    step_y = 1.0 / h if steps is None or steps[0] <= 0 else float(steps[0])
    step_x = 1.0 / w if steps is None or steps[1] <= 0 else float(steps[1])
    cy = (jnp.arange(h, dtype=jnp.float32) + float(offsets[0])) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + float(offsets[1])) * step_x
    aspect = float(h) / float(w)
    wh = []
    for s in sizes:                      # all sizes at ratios[0]
        sr = _np.sqrt(ratios[0])
        wh.append((s * aspect * sr / 2.0, s / sr / 2.0))
    for r in ratios[1:]:                 # remaining ratios at sizes[0]
        sr = _np.sqrt(r)
        wh.append((sizes[0] * aspect * sr / 2.0, sizes[0] / sr / 2.0))
    wh = jnp.asarray(wh, jnp.float32)                     # (A, 2)
    ctr = jnp.stack(jnp.meshgrid(cx, cy), axis=-1)        # (h, w, 2) [x, y]
    ctr = ctr.reshape(h * w, 1, 2)
    boxes = jnp.concatenate([ctr - wh[None], ctr + wh[None]], axis=-1)
    boxes = boxes.reshape(1, -1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


@register("_contrib_MultiBoxTarget", arity=3, differentiable=False,
          num_outputs=3)
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5, minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2)):
    """Match anchors to ground truth and emit SSD training targets
    (reference: multibox_target.cc). anchor (1, N, 4) corner; label
    (B, M, 5) [cls, x1, y1, x2, y2] padded with cls=-1; cls_pred
    (B, C+1, N) (used only for negative mining). Returns
    (box_target (B, N*4), box_mask (B, N*4), cls_target (B, N))."""
    f = jnp.float32
    a = anchor.astype(f).reshape(-1, 4)                   # (N, 4)
    n = a.shape[0]
    lab = label.astype(f)
    if lab.ndim == 2:
        lab = lab[None]
    b, m, _ = lab.shape
    gt_cls = lab[..., 0]                                  # (B, M), -1 = pad
    gt_box = lab[..., 1:5]
    gt_valid = gt_cls >= 0

    iou = _pair_iou(jnp.broadcast_to(a, (b, n, 4)), gt_box)   # (B, N, M)
    iou = jnp.where(gt_valid[:, None, :], iou, -1.0)

    # stage 1 (bipartite-greedy in the reference; argmax approximation):
    # each valid GT claims its best anchor unconditionally
    best_anchor = jnp.argmax(iou, axis=1)                 # (B, M)
    claimed = jnp.zeros((b, n), bool)
    claimed_gt = jnp.full((b, n), -1, jnp.int32)

    def claim(j, st):
        claimed, claimed_gt = st
        idx = best_anchor[:, j]
        # a GT with zero IoU against every anchor (degenerate box) must not
        # claim one — the reference skips unmatched GTs
        has_overlap = jnp.max(iou[:, :, j], axis=1) > 0
        ok = gt_valid[:, j] & has_overlap & ~jnp.take_along_axis(
            claimed, idx[:, None], axis=1)[:, 0]
        claimed = claimed.at[jnp.arange(b), idx].set(
            claimed[jnp.arange(b), idx] | ok)
        claimed_gt = claimed_gt.at[jnp.arange(b), idx].set(
            jnp.where(ok, j, claimed_gt[jnp.arange(b), idx]))
        return claimed, claimed_gt

    claimed, claimed_gt = lax.fori_loop(0, m, claim, (claimed, claimed_gt))

    # stage 2: remaining anchors match their best GT if IoU > threshold
    best_gt = jnp.argmax(iou, axis=2)                     # (B, N)
    best_iou = jnp.max(iou, axis=2)
    thresh_ok = best_iou >= overlap_threshold
    match = jnp.where(claimed, claimed_gt,
                      jnp.where(thresh_ok, best_gt, -1))  # (B, N)
    pos = match >= 0

    mg = jnp.clip(match, 0, m - 1)
    g = jnp.take_along_axis(gt_box, mg[..., None], axis=1)    # (B, N, 4)
    gc = _to_center(g)
    ac = _to_center(a)[None]
    v = variances
    t = jnp.stack([
        (gc[..., 0] - ac[..., 0]) / jnp.maximum(ac[..., 2], 1e-12) / v[0],
        (gc[..., 1] - ac[..., 1]) / jnp.maximum(ac[..., 3], 1e-12) / v[1],
        jnp.log(jnp.maximum(gc[..., 2] / jnp.maximum(ac[..., 2], 1e-12),
                            1e-12)) / v[2],
        jnp.log(jnp.maximum(gc[..., 3] / jnp.maximum(ac[..., 3], 1e-12),
                            1e-12)) / v[3]], axis=-1)
    box_target = jnp.where(pos[..., None], t, 0.0).reshape(b, n * 4)
    box_mask = jnp.where(pos[..., None],
                         jnp.ones((), f), 0.0)
    box_mask = jnp.broadcast_to(box_mask, (b, n, 4)).reshape(b, n * 4)

    cls_matched = jnp.take_along_axis(gt_cls, mg, axis=1)     # (B, N)
    cls_target = jnp.where(pos, cls_matched + 1.0, 0.0)       # 0 = background

    if negative_mining_ratio is not None and negative_mining_ratio > 0:
        # hard-negative mining: keep the ratio*num_pos highest-loss
        # negatives (proxied by background confidence deficit), rest ignored
        bg_prob = cls_pred.astype(f)[:, 0, :]                 # (B, N)
        neg_score = -bg_prob                                  # harder = higher
        neg = ~pos & (best_iou < negative_mining_thresh)
        num_pos = jnp.sum(pos, axis=1, keepdims=True).astype(f)
        quota = jnp.maximum(num_pos * float(negative_mining_ratio),
                            float(minimum_negative_samples))
        rank = jnp.argsort(jnp.argsort(
            jnp.where(neg, neg_score, -jnp.inf), axis=1, descending=True),
            axis=1).astype(f)
        keep_neg = neg & (rank < quota)
        cls_target = jnp.where(pos | keep_neg, cls_target,
                               float(ignore_label))
    return box_target, box_mask, cls_target


@register("_contrib_MultiBoxDetection", arity=3, differentiable=False)
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0, nms_threshold=0.5,
                        force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode predictions against anchors and NMS (reference:
    multibox_detection.cc). cls_prob (B, C+1, N), loc_pred (B, N*4),
    anchor (1, N, 4) -> (B, N, 6) rows [cls_id, score, x1, y1, x2, y2],
    suppressed rows get cls_id -1."""
    f = jnp.float32
    p = cls_prob.astype(f)
    b, _, n = p.shape
    loc = loc_pred.astype(f).reshape(b, n, 4)
    v = variances
    boxes = _box_decode(loc, anchor.astype(f).reshape(1, -1, 4),
                        std0=v[0], std1=v[1], std2=v[2], std3=v[3],
                        format="corner")
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)

    # per-anchor best foreground class
    fg = jnp.concatenate([p[:, :background_id], p[:, background_id + 1:]],
                         axis=1)                              # (B, C, N)
    cls_id = jnp.argmax(fg, axis=1).astype(f)                 # (B, N)
    score = jnp.max(fg, axis=1)
    valid = score > threshold
    rows = jnp.concatenate([
        jnp.where(valid, cls_id, -1.0)[..., None],
        jnp.where(valid, score, -1.0)[..., None], boxes], axis=-1)
    out = _box_nms(rows, overlap_thresh=nms_threshold,
                   valid_thresh=threshold, topk=nms_topk,
                   coord_start=2, score_index=1, id_index=0,
                   force_suppress=force_suppress)
    # reference convention: suppressed rows flagged via cls_id -1
    sup = out[..., 1] <= 0
    out = out.at[..., 0].set(jnp.where(sup, -1.0, out[..., 0]))
    return out


# ---------------------------------------------------------------------------
# DeformableConvolution (reference: contrib/deformable_convolution.cc,
# Dai et al. 2017) and PSROIPooling (contrib/psroi_pooling.cc, R-FCN).
# TPU-first: the deformable sampling is a static unroll over kernel taps —
# each tap is one vectorized bilinear gather over the whole batch, and the
# channel contraction stays a single einsum on the MXU per tap group.
# ---------------------------------------------------------------------------
def _bilinear_gather(img, ys, xs):
    """img (C, H, W); ys/xs (Ho, Wo) fractional coords -> (C, Ho, Wo).
    Corner taps outside the image contribute zero — the value decays
    bilinearly to zero across the border instead of clamping to the edge
    pixel, exactly the reference's dmcn_im2col_bilinear behavior (also
    what keeps the offset gradient alive at image edges)."""
    h, w = img.shape[1], img.shape[2]
    y0f = jnp.floor(ys)
    x0f = jnp.floor(xs)
    yf = (ys - y0f)[None]
    xf = (xs - x0f)[None]
    y0 = y0f.astype(jnp.int32)
    x0 = x0f.astype(jnp.int32)

    def corner(yi, xi):
        ok = ((yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)) \
            .astype(jnp.float32)
        v = img[:, jnp.clip(yi, 0, h - 1), jnp.clip(xi, 0, w - 1)]
        return v * ok[None]

    return (corner(y0, x0) * (1 - yf) * (1 - xf) +
            corner(y0, x0 + 1) * (1 - yf) * xf +
            corner(y0 + 1, x0) * yf * (1 - xf) +
            corner(y0 + 1, x0 + 1) * yf * xf)


@register("_contrib_DeformableConvolution", arity=3)
def _deformable_convolution(data, offset, weight, bias=None, kernel=None,
                            stride=None, dilate=None, pad=None,
                            num_filter=None, num_group=1,
                            num_deformable_group=1, no_bias=False,
                            layout=None, workspace=None):
    """data (N, C, H, W); offset (N, 2*dg*kh*kw, Ho, Wo) ordered
    [y, x] per tap per deformable group; weight (O, C/g, kh, kw)."""
    if num_group != 1:
        raise NotImplementedError("DeformableConvolution: num_group > 1")
    from .nn import layout_info
    _, last = layout_info(layout, 2, "DeformableConvolution")
    if last:
        raise NotImplementedError(
            "DeformableConvolution: channels-last layouts not implemented")
    kh, kw = kernel
    stride = _pair(stride if stride else 1, 2)
    dilate = _pair(dilate if dilate else 1, 2)
    pad = _pair(pad if pad else 0, 2)
    n, c, h, w = data.shape
    ho = (h + 2 * pad[0] - (dilate[0] * (kh - 1) + 1)) // stride[0] + 1
    wo = (w + 2 * pad[1] - (dilate[1] * (kw - 1) + 1)) // stride[1] + 1
    dg = num_deformable_group
    if c % dg != 0:
        raise ValueError(
            "DeformableConvolution: channels (%d) must divide evenly into "
            "num_deformable_group (%d)" % (c, dg))
    cg = c // dg
    f32 = data.astype(jnp.float32)
    off = offset.astype(jnp.float32).reshape(n, dg, kh * kw, 2, ho, wo)

    base_y = (jnp.arange(ho) * stride[0] - pad[0])[:, None]      # (Ho, 1)
    base_x = (jnp.arange(wo) * stride[1] - pad[1])[None, :]      # (1, Wo)

    out = jnp.zeros((n, num_filter, ho, wo), jnp.float32)
    wgt = weight.astype(jnp.float32)
    for k in range(kh * kw):
        ky, kx = k // kw, k % kw
        for g in range(dg):
            ys = base_y + ky * dilate[0] + off[:, g, k, 0]       # (N, Ho, Wo)
            xs = base_x + kx * dilate[1] + off[:, g, k, 1]
            sampled = jax.vmap(_bilinear_gather)(
                f32[:, g * cg:(g + 1) * cg], ys, xs)             # (N,cg,Ho,Wo)
            out = out + jnp.einsum("nchw,oc->nohw", sampled,
                                   wgt[:, g * cg:(g + 1) * cg, ky, kx])
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32).reshape(1, -1, 1, 1)
    return out.astype(data.dtype)


@register("_contrib_PSROIPooling", arity=2)
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=None,
                   pooled_size=None, group_size=None):
    """Position-sensitive ROI pooling (reference: psroi_pooling.cc).
    data (N, output_dim*ps*ps, H, W); rois (R, 5) [b, x1, y1, x2, y2];
    output (R, output_dim, ps, ps) — bin (i, j) averages its OWN channel
    slice over its sub-window. Masked means keep every shape static."""
    ps = int(pooled_size)
    if group_size is not None and int(group_size) != ps:
        raise NotImplementedError("PSROIPooling: group_size != pooled_size")
    n, ctot, h, w = data.shape
    od = int(output_dim)
    f32 = data.astype(jnp.float32).reshape(n, od, ps, ps, h, w)

    hh = jnp.arange(h, dtype=jnp.float32)
    ww = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        # reference psroi_pooling.cc: start = round(coord)*scale,
        # end = (round(coord)+1)*scale — the window includes the end
        # pixel. C round() is half-away-from-zero: floor(x+0.5) for the
        # non-negative coords here (jnp.round is half-to-even).
        x1 = jnp.floor(roi[1] + 0.5) * spatial_scale
        y1 = jnp.floor(roi[2] + 0.5) * spatial_scale
        x2 = (jnp.floor(roi[3] + 0.5) + 1.0) * spatial_scale
        y2 = (jnp.floor(roi[4] + 0.5) + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / ps, rw / ps
        # bin windows [floor(start), ceil(end)) as row/col masks
        i = jnp.arange(ps, dtype=jnp.float32)
        hs = jnp.floor(y1 + i * bh)            # (ps,)
        he = jnp.ceil(y1 + (i + 1) * bh)
        ws_ = jnp.floor(x1 + i * bw)
        we = jnp.ceil(x1 + (i + 1) * bw)
        rmask = ((hh[None, :] >= hs[:, None]) &
                 (hh[None, :] < he[:, None])).astype(jnp.float32)  # (ps, H)
        cmask = ((ww[None, :] >= ws_[:, None]) &
                 (ww[None, :] < we[:, None])).astype(jnp.float32)  # (ps, W)
        img = f32[bidx]                                  # (od, ps, ps, H, W)
        num = jnp.einsum("dijhw,ih,jw->dij", img, rmask, cmask)
        cnt = jnp.einsum("ih,jw->ij", rmask, cmask)
        return num / jnp.maximum(cnt, 1.0)[None]

    out = jax.vmap(one_roi)(rois.astype(jnp.float32))
    return out.astype(data.dtype)
