"""Optimizer API. reference: python/mxnet/optimizer/__init__.py."""
from . import optimizer
from .optimizer import *  # noqa: F401,F403
from .optimizer import Optimizer, create, register, get_updater, Updater

__all__ = optimizer.__all__
