"""Convolution & pooling layers.
reference: python/mxnet/gluon/nn/conv_layers.py.

Both channels-first (NCW/NCHW/NCDHW, the reference default) and
channels-last (NWC/NHWC/NDHWC) layouts are supported end-to-end; XLA
relayouts to the TPU-native tiling internally either way.
"""
from __future__ import annotations

from ..block import HybridBlock
from .activations import Activation

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D", "ReflectionPad2D"]


def _to_tuple(x, n):
    if isinstance(x, int):
        return (x,) * n
    assert len(x) == n
    return tuple(x)


class _Conv(HybridBlock):
    """Base conv. reference: nn/conv_layers.py (_Conv)."""

    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros", op_name="Convolution",
                 adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._channels = channels
            self._in_channels = in_channels
            from ...ops.nn import layout_info
            _, self._channels_last = layout_info(
                layout, len(kernel_size), type(self).__name__)
            self._kwargs = {
                "kernel": kernel_size, "stride": strides, "dilate": dilation,
                "pad": padding, "num_filter": channels, "num_group": groups,
                "no_bias": not use_bias, "layout": layout}
            if adj is not None:
                self._kwargs["adj"] = adj
            self._op_name = op_name

            if op_name == "Convolution":
                if self._channels_last:
                    # reference NHWC weight layout: (O, *kernel, I/groups)
                    wshape = (channels,) + kernel_size + \
                        (in_channels // groups,)
                else:
                    wshape = (channels, in_channels // groups) + kernel_size
            else:  # Deconvolution: weight is (in, out//groups, *k)
                assert not self._channels_last, \
                    "Deconvolution supports channels-first layouts only"
                wshape = (in_channels, channels // groups) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(channels,), init=bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def _shape_from_input(self, x, *args):
        in_channels = x.shape[-1 if self._channels_last else 1]
        k = self._kwargs["kernel"]
        groups = self._kwargs["num_group"]
        if self._op_name == "Convolution":
            if self._channels_last:
                self.weight.shape = (self._channels,) + k + \
                    (in_channels // groups,)
            else:
                self.weight.shape = \
                    (self._channels, in_channels // groups) + k
        else:
            self.weight.shape = (in_channels, self._channels // groups) + k
        self._in_channels = in_channels

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        if bias is None:
            act = op(x, weight, **self._kwargs)
        else:
            act = op(x, weight, bias, **self._kwargs)
        if self.act is not None:
            act = self.act(act)
        return act

    def _alias(self):
        return "conv"

    def __repr__(self):
        s = "{name}({mapping}, kernel_size={kernel}, stride={stride}"
        len_kernel_size = len(self._kwargs["kernel"])
        if self._kwargs["pad"] != (0,) * len_kernel_size:
            s += ", padding={pad}"
        if self._kwargs["dilate"] != (1,) * len_kernel_size:
            s += ", dilation={dilate}"
        if hasattr(self, "out_pad") and self.out_pad != (0,) * len_kernel_size:
            s += ", output_padding={out_pad}".format(out_pad=self.out_pad)
        if self._kwargs["num_group"] != 1:
            s += ", groups={num_group}"
        if self.bias is None:
            s += ", bias=False"
        if self.act:
            s += ", {}".format(self.act)
        s += ")"
        shape = self.weight.shape
        in_ch = shape[-1] if self._channels_last else shape[1]
        return s.format(name=self.__class__.__name__,
                        mapping="{0} -> {1}".format(
                            in_ch if in_ch else None, shape[0]),
                        **self._kwargs)


class Conv1D(_Conv):
    """reference: nn/conv_layers.py (Conv1D)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 1)
        strides = _to_tuple(strides, 1)
        padding = _to_tuple(padding, 1)
        dilation = _to_tuple(dilation, 1)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2D(_Conv):
    """reference: nn/conv_layers.py (Conv2D)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 2)
        strides = _to_tuple(strides, 2)
        padding = _to_tuple(padding, 2)
        dilation = _to_tuple(dilation, 2)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3D(_Conv):
    """reference: nn/conv_layers.py (Conv3D)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        kernel_size = _to_tuple(kernel_size, 3)
        strides = _to_tuple(strides, 3)
        padding = _to_tuple(padding, 3)
        dilation = _to_tuple(dilation, 3)
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class _ConvTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides, padding,
                 output_padding, dilation, groups, layout, in_channels,
                 activation, use_bias, weight_initializer, bias_initializer,
                 **kwargs):
        super().__init__(channels, kernel_size, strides, padding, dilation,
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer,
                         op_name="Deconvolution", adj=output_padding, **kwargs)
        self.outpad = output_padding
        self.out_pad = output_padding


class Conv1DTranspose(_ConvTranspose):
    """reference: nn/conv_layers.py (Conv1DTranspose)."""

    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _to_tuple(kernel_size, 1),
                         _to_tuple(strides, 1), _to_tuple(padding, 1),
                         _to_tuple(output_padding, 1), _to_tuple(dilation, 1),
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv2DTranspose(_ConvTranspose):
    """reference: nn/conv_layers.py (Conv2DTranspose)."""

    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, **kwargs):
        super().__init__(channels, _to_tuple(kernel_size, 2),
                         _to_tuple(strides, 2), _to_tuple(padding, 2),
                         _to_tuple(output_padding, 2), _to_tuple(dilation, 2),
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class Conv3DTranspose(_ConvTranspose):
    """reference: nn/conv_layers.py (Conv3DTranspose)."""

    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, **kwargs):
        super().__init__(channels, _to_tuple(kernel_size, 3),
                         _to_tuple(strides, 3), _to_tuple(padding, 3),
                         _to_tuple(output_padding, 3), _to_tuple(dilation, 3),
                         groups, layout, in_channels, activation, use_bias,
                         weight_initializer, bias_initializer, **kwargs)


class _Pooling(HybridBlock):
    """Base pooling. reference: nn/conv_layers.py (_Pooling)."""

    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        from ...ops.nn import layout_info
        layout_info(layout, len(pool_size), type(self).__name__)
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "global_pool": global_pool, "pool_type": pool_type,
            "layout": layout,
            "pooling_convention": "full" if ceil_mode else "valid"}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return "{name}(size={kernel}, stride={stride}, padding={pad}, " \
            "ceil_mode={ceil_mode})".format(
                name=self.__class__.__name__,
                ceil_mode=self._kwargs["pooling_convention"] == "full",
                **self._kwargs)


class MaxPool1D(_Pooling):
    """reference: nn/conv_layers.py (MaxPool1D)."""

    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_to_tuple(pool_size, 1),
                         strides if strides is None else _to_tuple(strides, 1),
                         _to_tuple(padding, 1), ceil_mode, False, "max",
                         layout, **kwargs)


class MaxPool2D(_Pooling):
    """reference: nn/conv_layers.py (MaxPool2D)."""

    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_to_tuple(pool_size, 2),
                         strides if strides is None else _to_tuple(strides, 2),
                         _to_tuple(padding, 2), ceil_mode, False, "max",
                         layout, **kwargs)


class MaxPool3D(_Pooling):
    """reference: nn/conv_layers.py (MaxPool3D)."""

    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_to_tuple(pool_size, 3),
                         strides if strides is None else _to_tuple(strides, 3),
                         _to_tuple(padding, 3), ceil_mode, False, "max",
                         layout, **kwargs)


class AvgPool1D(_Pooling):
    """reference: nn/conv_layers.py (AvgPool1D)."""

    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_to_tuple(pool_size, 1),
                         strides if strides is None else _to_tuple(strides, 1),
                         _to_tuple(padding, 1), ceil_mode, False, "avg",
                         layout, count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    """reference: nn/conv_layers.py (AvgPool2D)."""

    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_to_tuple(pool_size, 2),
                         strides if strides is None else _to_tuple(strides, 2),
                         _to_tuple(padding, 2), ceil_mode, False, "avg",
                         layout, count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    """reference: nn/conv_layers.py (AvgPool3D)."""

    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_to_tuple(pool_size, 3),
                         strides if strides is None else _to_tuple(strides, 3),
                         _to_tuple(padding, 3), ceil_mode, False, "avg",
                         layout, count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    """reference: nn/conv_layers.py (GlobalMaxPool1D)."""

    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), True, True, "max", layout,
                         **kwargs)


class GlobalMaxPool2D(_Pooling):
    """reference: nn/conv_layers.py (GlobalMaxPool2D)."""

    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "max", layout,
                         **kwargs)


class GlobalMaxPool3D(_Pooling):
    """reference: nn/conv_layers.py (GlobalMaxPool3D)."""

    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "max",
                         layout, **kwargs)


class GlobalAvgPool1D(_Pooling):
    """reference: nn/conv_layers.py (GlobalAvgPool1D)."""

    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), True, True, "avg", layout,
                         **kwargs)


class GlobalAvgPool2D(_Pooling):
    """reference: nn/conv_layers.py (GlobalAvgPool2D)."""

    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), True, True, "avg", layout,
                         **kwargs)


class GlobalAvgPool3D(_Pooling):
    """reference: nn/conv_layers.py (GlobalAvgPool3D)."""

    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), True, True, "avg",
                         layout, **kwargs)


class ReflectionPad2D(HybridBlock):
    """reference: nn/conv_layers.py (ReflectionPad2D)."""

    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        if isinstance(padding, int):
            padding = (0, 0, 0, 0, padding, padding, padding, padding)
        self._padding = padding

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._padding)
