"""Activation blocks. reference: python/mxnet/gluon/nn/activations.py."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish",
           "GELU"]


class Activation(HybridBlock):
    """reference: gluon/nn/activations.py (Activation)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return "{name}({_act_type})".format(
            name=self.__class__.__name__, **self.__dict__)


class LeakyReLU(HybridBlock):
    """reference: gluon/nn/activations.py (LeakyReLU)."""

    def __init__(self, alpha, **kwargs):
        assert alpha >= 0, "Slope coefficient for LeakyReLU must be no less " \
                           "than 0."
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return "{name}({alpha})".format(
            name=self.__class__.__name__, alpha=self._alpha)


class PReLU(HybridBlock):
    """reference: gluon/nn/activations.py (PReLU) — learnable slope."""

    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as _init
        if alpha_initializer is None:
            alpha_initializer = _init.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(1,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    """reference: gluon/nn/activations.py (ELU)."""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """reference: gluon/nn/activations.py (SELU)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    """reference: gluon/nn/activations.py (Swish) — x * sigmoid(beta x)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    """reference: gluon/nn/activations.py (GELU)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")
