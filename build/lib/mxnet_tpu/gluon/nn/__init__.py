"""Gluon neural-network layers. reference: python/mxnet/gluon/nn/__init__.py."""
from .activations import *  # noqa: F401,F403
from .basic_layers import *  # noqa: F401,F403
from .conv_layers import *  # noqa: F401,F403

from . import activations, basic_layers, conv_layers

__all__ = (activations.__all__ + basic_layers.__all__ +
           conv_layers.__all__)
