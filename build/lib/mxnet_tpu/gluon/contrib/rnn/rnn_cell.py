"""reference: python/mxnet/gluon/contrib/rnn/rnn_cell.py."""
from __future__ import annotations

from ...rnn.rnn_cell import ModifierCell
from .... import ndarray as nd


class VariationalDropoutCell(ModifierCell):
    """Dropout with masks drawn ONCE per sequence and reused at every step
    (Gal & Ghahramani; reference: contrib/rnn VariationalDropoutCell)."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        self._drop_inputs = drop_inputs
        self._drop_states = drop_states
        self._drop_outputs = drop_outputs
        super().__init__(base_cell)
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    @staticmethod
    def _mask(p, like):
        return nd.invoke("Dropout", nd.ones_like(like), p=p)

    def hybrid_forward(self, F, inputs, states):
        if self._drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask(self._drop_inputs, inputs)
            inputs = inputs * self._input_mask
        if self._drop_states:
            if self._state_masks is None:
                self._state_masks = [self._mask(self._drop_states, s)
                                     for s in states]
            states = [s * m for s, m in zip(states, self._state_masks)]
        out, next_states = self.base_cell(inputs, states)
        if self._drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask(self._drop_outputs, out)
            out = out * self._output_mask
        return out, next_states
