"""`gluon.contrib.rnn` (reference: python/mxnet/gluon/contrib/rnn/
conv_rnn_cell.py, rnn_cell.py) — VariationalDropoutCell plus re-exports of
the shared cell surface."""
from ...rnn import (RNNCell, LSTMCell, GRUCell, SequentialRNNCell,
                    BidirectionalCell, DropoutCell, ResidualCell,
                    ZoneoutCell, ModifierCell)
from .rnn_cell import VariationalDropoutCell

__all__ = ["RNNCell", "LSTMCell", "GRUCell", "SequentialRNNCell",
           "BidirectionalCell", "DropoutCell", "ResidualCell",
           "ZoneoutCell", "VariationalDropoutCell"]
