"""`gluon.contrib.nn` layers.

reference: python/mxnet/gluon/contrib/nn/basic_layers.py (Concurrent,
HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm). SyncBatchNorm
here IS BatchNorm: under GSPMD, batch statistics reduce over the sharded
batch axis automatically inside jit, which is the whole point of the
reference's cross-device sync kernel.
"""
from .basic_layers import (Concurrent, HybridConcurrent, Identity,
                           SparseEmbedding, SyncBatchNorm)

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm"]
