"""reference: python/mxnet/gluon/contrib/nn/basic_layers.py."""
from __future__ import annotations

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential, BatchNorm, Embedding


class Concurrent(Sequential):
    """Children run on the same input; outputs concat on `axis`.
    reference: contrib/nn (Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray as nd
        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """reference: contrib/nn (HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """reference: contrib/nn (Identity)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding with row_sparse gradients (reference: contrib/nn
    (SparseEmbedding) — sparse grad for kvstore row_sparse push/pull)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "sparse_grad": True}
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), init=weight_initializer,
            dtype=dtype, grad_stype="row_sparse")

    def forward(self, x):
        from .... import ndarray as nd
        return nd.invoke("Embedding", x, self.weight.data(x.context),
                         **{k: v for k, v in self._kwargs.items()
                            if k != "sparse_grad"})

    def __repr__(self):
        return "SparseEmbedding(%d -> %d)" % (self._kwargs["input_dim"],
                                              self._kwargs["output_dim"])


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm. reference: contrib/nn
    (SyncBatchNorm, sync_batch_norm.cu). Under GSPMD a batch-sharded input
    reduces its statistics over the global batch automatically inside the
    jitted program, so the base BatchNorm already IS synchronized; the
    class exists for API parity and ignores num_devices/key."""

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=
                         running_variance_initializer,
                         in_channels=in_channels, **kwargs)
