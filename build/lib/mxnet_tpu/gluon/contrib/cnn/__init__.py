"""`gluon.contrib.cnn` (reference: python/mxnet/gluon/contrib/cnn/)."""
from .conv_layers import (DeformableConvolution,  # noqa: F401
                          FusedConvBNReLU, FusedConvBNReLUTrain)

__all__ = ["DeformableConvolution", "FusedConvBNReLU",
           "FusedConvBNReLUTrain"]
