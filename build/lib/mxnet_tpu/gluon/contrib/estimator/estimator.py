"""Estimator — the high-level Gluon fit loop.

reference: python/mxnet/gluon/contrib/estimator/estimator.py — wraps
net/loss/metrics/trainer into `fit(train_data, val_data, epochs)` with
lifecycle event handlers. The step itself is the standard
record/backward/step triple; on TPU the hybridized net makes each batch
one XLA program.
"""
from __future__ import annotations

import logging

from .... import autograd, metric as metric_mod
from ... import Trainer
from ...loss import Loss
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StoppingHandler,
                            TrainBegin, TrainEnd, ValidationHandler)

__all__ = ["Estimator"]


class _LossMetric(metric_mod.EvalMetric):
    """Running mean of the loss (reference: metric.Loss)."""

    def __init__(self, name="loss"):
        super().__init__(name)

    def update(self, _labels, preds):
        import numpy as onp
        arr = preds.asnumpy() if hasattr(preds, "asnumpy") else \
            onp.asarray(preds)
        self.sum_metric += float(arr.sum())
        self.num_inst += int(arr.size)


class Estimator:
    """reference: gluon.contrib.estimator.Estimator."""

    def __init__(self, net, loss, metrics=None, trainer=None, context=None,
                 logger=None):
        self.net = net
        if not isinstance(loss, Loss):
            raise ValueError("loss must be a gluon.loss.Loss, got %s"
                             % type(loss))
        self.loss = loss
        if metrics is None:
            metrics = []
        elif isinstance(metrics, metric_mod.EvalMetric):
            metrics = [metrics]
        self.train_metrics = list(metrics)
        self.train_loss_metric = _LossMetric("train_loss")
        self.val_metrics = [m.__class__() for m in self.train_metrics]
        self.val_loss_metric = _LossMetric("val_loss")
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.context = context
        self.logger = logger or logging.getLogger("Estimator")
        self.stop_training = False

    # ------------------------------------------------------------------
    def _place(self, x, y):
        if self.context is not None:
            x = x.as_in_context(self.context)
            y = y.as_in_context(self.context)
        return x, y

    def evaluate(self, val_data):
        """One pass over val_data updating the val metrics."""
        for m in self.val_metrics:
            m.reset()
        self.val_loss_metric.reset()
        for batch in val_data:
            x, y = self._place(batch[0], batch[1])
            pred = self.net(x)
            loss = self.loss(pred, y)
            for m in self.val_metrics:
                m.update([y], [pred])
            self.val_loss_metric.update(0, loss)
        return dict(m.get() for m in
                    self.val_metrics + [self.val_loss_metric])

    def _default_handlers(self, val_data, epochs):
        handlers = [StoppingHandler(max_epoch=epochs),
                    MetricHandler(self.train_metrics +
                                  [self.train_loss_metric])]
        if val_data is not None:
            handlers.append(ValidationHandler(val_data, self.evaluate))
        handlers.append(LoggingHandler(
            metrics=self.train_metrics + [self.train_loss_metric],
            logger=self.logger))
        return handlers

    def fit(self, train_data, val_data=None, epochs=1, event_handlers=None,
            batch_axis=0):
        """reference: Estimator.fit — the event-driven epoch/batch loop."""
        handlers = list(event_handlers) if event_handlers else []
        defaults_needed = not any(isinstance(h, StoppingHandler)
                                  for h in handlers)
        if defaults_needed:
            handlers = self._default_handlers(val_data, epochs) + handlers

        def emit(kind, **kwargs):
            base = {"TrainBegin": TrainBegin, "TrainEnd": TrainEnd,
                    "EpochBegin": EpochBegin, "EpochEnd": EpochEnd,
                    "BatchBegin": BatchBegin, "BatchEnd": BatchEnd}[kind]
            meth = {"TrainBegin": "train_begin", "TrainEnd": "train_end",
                    "EpochBegin": "epoch_begin", "EpochEnd": "epoch_end",
                    "BatchBegin": "batch_begin", "BatchEnd": "batch_end"}
            for h in handlers:
                if isinstance(h, base):
                    getattr(h, meth[kind])(self, **kwargs)
            self.stop_training = any(
                getattr(h, "stop_training", False) for h in handlers)

        emit("TrainBegin")
        while not self.stop_training:
            emit("EpochBegin")
            for batch in train_data:
                x, y = self._place(batch[0], batch[1])
                emit("BatchBegin")
                with autograd.record():
                    pred = self.net(x)
                    loss = self.loss(pred, y)
                loss.backward()
                self.trainer.step(x.shape[batch_axis])
                emit("BatchEnd", pred=pred, label=y, loss=loss)
                if self.stop_training:
                    break
            emit("EpochEnd")
        emit("TrainEnd")
        return self
