"""Estimator event handlers.

reference: python/mxnet/gluon/contrib/estimator/event_handler.py — the
fit loop emits lifecycle events (train/epoch/batch begin+end) and
handlers mix in the hooks they care about: metric logging, validation,
checkpointing, early stopping.
"""
from __future__ import annotations

import logging
import os
import time

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop on max_epoch / max_batch (reference: StoppingHandler)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False     # reusable across fit() calls

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Updates train metrics every batch; resets per epoch
    (reference: MetricHandler)."""

    def __init__(self, metrics):
        self.metrics = metrics or []

    def epoch_begin(self, estimator, *args, **kwargs):
        for m in self.metrics:
            m.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for m in self.metrics:
            # loss metrics (e.g. "train_loss"/"val_loss") consume the loss
            # tensor; everything else scores predictions against labels
            if "loss" in getattr(m, "name", "") and loss is not None:
                m.update(0, loss)
            elif pred is not None and label is not None:
                m.update([label], [pred])


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Runs evaluation on a schedule (reference: ValidationHandler)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and \
                self.current_batch % self.batch_period == 0:
            self.eval_fn(self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            self.eval_fn(self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Logs metrics per epoch (and optionally per N batches)
    (reference: LoggingHandler)."""

    def __init__(self, log_interval="epoch", metrics=None, logger=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.logger = logger or logging.getLogger("Estimator")
        self.batch_index = 0
        self.current_epoch = 0
        self._train_start = None
        self._epoch_start = None

    def train_begin(self, estimator, *args, **kwargs):
        self._train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        self.logger.info("Training finished in %.1fs",
                         time.time() - self._train_start)

    def epoch_begin(self, estimator, *args, **kwargs):
        self._epoch_start = time.time()
        self.batch_index = 0

    def epoch_end(self, estimator, *args, **kwargs):
        msg = "Epoch %d  %.1fs  " % (self.current_epoch,
                                     time.time() - self._epoch_start)
        msg += "  ".join("%s: %.4f" % m.get() for m in self.metrics)
        self.logger.info(msg)
        self.current_epoch += 1

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        if isinstance(self.log_interval, int) and \
                self.batch_index % self.log_interval == 0:
            msg = "[Epoch %d][Batch %d] " % (self.current_epoch,
                                             self.batch_index)
            msg += "  ".join("%s: %.4f" % m.get() for m in self.metrics)
            self.logger.info(msg)


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Saves model parameters (and trainer states) on a schedule; can
    track a monitored metric and keep the best checkpoint
    (reference: CheckpointHandler)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 mode="min", epoch_period=1, save_best=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.mode = mode
        self.epoch_period = epoch_period
        self.save_best = save_best
        self.current_epoch = 0
        self.best = None

    def train_begin(self, estimator, *args, **kwargs):
        os.makedirs(self.model_dir, exist_ok=True)
        self.current_epoch = 0
        self.best = None

    def _improved(self, value):
        if self.best is None:
            return True
        return value < self.best if self.mode == "min" else value > self.best

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and \
                self.current_epoch % self.epoch_period == 0:
            path = os.path.join(self.model_dir, "%s-epoch%d.params"
                                % (self.model_prefix, self.current_epoch))
            estimator.net.save_parameters(path)
        if self.save_best and self.monitor is not None:
            _, value = self.monitor.get()
            if self._improved(value):
                self.best = value
                estimator.net.save_parameters(os.path.join(
                    self.model_dir, "%s-best.params" % self.model_prefix))


class EarlyStoppingHandler(TrainBegin, EpochEnd):
    """Stops training when the monitored metric stops improving
    (reference: EarlyStoppingHandler)."""

    def __init__(self, monitor, mode="min", patience=3, min_delta=0.0):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.stop_training = False
        self.stopped_epoch = None
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.best = None
        self.wait = 0
        self.stop_training = False
        self.current_epoch = 0

    def _improved(self, value):
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        _, value = self.monitor.get()
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                self.stopped_epoch = self.current_epoch
