"""gluon.contrib.estimator (reference:
python/mxnet/gluon/contrib/estimator/) — high-level fit loop + event
handlers."""
from .estimator import Estimator
from .event_handler import (TrainBegin, TrainEnd, EpochBegin, EpochEnd,
                            BatchBegin, BatchEnd, StoppingHandler,
                            MetricHandler, ValidationHandler,
                            LoggingHandler, CheckpointHandler,
                            EarlyStoppingHandler)

__all__ = ["Estimator", "TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd",
           "BatchBegin", "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]
