"""`gluon.contrib` (reference: python/mxnet/gluon/contrib/)."""
from . import cnn
from . import nn
from . import rnn
from . import estimator

__all__ = ["cnn", "nn", "rnn", "estimator"]
