"""Unfused recurrent cells. reference: python/mxnet/gluon/rnn/rnn_cell.py.

Same cell classes and `unroll` protocol as the reference. Under
`hybridize()` the python unroll loop is traced once and XLA compiles the
unrolled graph; the fused `lax.scan` path lives in rnn_layer.py.
"""
from __future__ import annotations

from ... import ndarray as nd
from ..block import Block, HybridBlock
from ..utils import _indent

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "HybridSequentialRNNCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell",
           "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        begin_state = cell.begin_state(batch_size=batch_size,
                                       func=F.zeros if hasattr(F, "zeros")
                                       else nd.zeros)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """Normalize inputs to a list of per-step tensors or one merged tensor.
    reference: rnn_cell.py (_format_sequence)."""
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    batch_size = 0
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, nd.NDArray) or not isinstance(inputs,
                                                        (list, tuple)):
        F = None
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            assert length is None or length == inputs.shape[in_axis]
            inputs = list(nd.split_v2(
                inputs, inputs.shape[in_axis], axis=in_axis,
                squeeze_axis=True)) if isinstance(inputs, nd.NDArray) else \
                [inputs.slice_axis(in_axis, i, i + 1).reshape(
                    _squeeze_shape(inputs, in_axis))
                 for i in range(inputs.shape[in_axis])]
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = nd.concat(*[i.expand_dims(axis) for i in inputs],
                               dim=axis)
    if isinstance(inputs, (list, tuple)):
        length = len(inputs)
    else:
        length = inputs.shape[in_axis] if merge is not True else length
    return inputs, axis, batch_size, length


def _squeeze_shape(x, axis):
    shape = list(x.shape)
    shape.pop(axis)
    return tuple(shape)


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merge):
    assert valid_length is not None
    if not isinstance(data, (list, tuple)):
        return F.SequenceMask(data, sequence_length=valid_length,
                              use_sequence_length=True, axis=time_axis)
    outputs = [
        F.SequenceMask(x.expand_dims(time_axis), sequence_length=valid_length,
                       use_sequence_length=True, axis=time_axis)
        for x in data]
    if merge:
        return nd.concat(*outputs, dim=time_axis)
    return [o.reshape(_squeeze_shape(o, time_axis)) for o in outputs]


class RecurrentCell(Block):
    """Abstract cell. reference: rnn_cell.py (RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        """Reset before re-use (new sequence)."""
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states. reference: RecurrentCell.begin_state."""
        assert not self._modified, \
            "After applying modifier cells (e.g. ZoneoutCell) the base cell " \
            "cannot be called directly. Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(name="%sbegin_state_%d" % (self._prefix,
                                                    self._init_counter),
                         **info) if _func_takes_name(func) else \
                func(info["shape"])
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell for `length` steps.
        reference: RecurrentCell.unroll."""
        self.reset()
        F = nd
        inputs, axis, batch_size, length = _format_sequence(
            length, inputs, layout, False)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [nd.invoke("SequenceLast",
                                nd.stack(*ele_list, axis=0),
                                valid_length,
                                use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(F, outputs, length,
                                                     valid_length, axis, True)
        if merge_outputs:
            # per-step (N,C) outputs -> one (.., T, ..) tensor on the
            # layout's time axis
            outputs = nd.concat(*[o.expand_dims(axis) for o in outputs],
                                dim=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        func = {"tanh": F.tanh, "relu": F.relu, "sigmoid": F.sigmoid,
                "softsign": lambda x: F.Activation(x, act_type="softsign")}
        if isinstance(activation, str):
            if activation in func:
                return func[activation](inputs, **kwargs) \
                    if activation not in ("tanh", "relu", "sigmoid") else \
                    getattr(inputs, activation)()
            return F.Activation(inputs, act_type=activation, **kwargs)
        if isinstance(activation, HybridBlock):
            return activation(inputs, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


def _func_takes_name(func):
    import inspect
    try:
        return "name" in inspect.signature(func).parameters
    except (ValueError, TypeError):
        return False


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """reference: rnn_cell.py (HybridRecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell. reference: rnn_cell.py (RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def _shape_from_input(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        i2h_plus_h2h = i2h + h2h
        output = self._get_activation(F, i2h_plus_h2h, self._activation)
        return output, [output]

    def __repr__(self):
        s = "{name}({mapping}"
        if hasattr(self, "_activation"):
            s += ", {_activation}"
        s += ")"
        shape = self.i2h_weight.shape
        mapping = "{0} -> {1}".format(shape[1] if shape[1] else None,
                                      shape[0])
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)


class LSTMCell(HybridRecurrentCell):
    """LSTM cell. reference: rnn_cell.py (LSTMCell). Gate order [i,f,g,o]."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None, activation="tanh",
                 recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)
        self._activation = activation
        self._recurrent_activation = recurrent_activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def _shape_from_input(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.split(gates, num_outputs=4, axis=-1)
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2],
                                            self._activation)
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]

    def __repr__(self):
        shape = self.i2h_weight.shape
        mapping = "{0} -> {1}".format(shape[1] if shape[1] else None,
                                      shape[0] // 4)
        return "{name}({mapping})".format(name=self.__class__.__name__,
                                          mapping=mapping)


class GRUCell(HybridRecurrentCell):
    """GRU cell. reference: rnn_cell.py (GRUCell). Gate order [r,z,n]."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def _shape_from_input(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.split(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h = F.split(h2h, num_outputs=3, axis=-1)
        reset_gate = (i2h_r + h2h_r).sigmoid()
        update_gate = (i2h_z + h2h_z).sigmoid()
        next_h_tmp = (i2h + reset_gate * h2h).tanh()
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]

    def __repr__(self):
        shape = self.i2h_weight.shape
        mapping = "{0} -> {1}".format(shape[1] if shape[1] else None,
                                      shape[0] // 3)
        return "{name}({mapping})".format(name=self.__class__.__name__,
                                          mapping=mapping)


class SequentialRNNCell(RecurrentCell):
    """Stack cells. reference: rnn_cell.py (SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        return s.format(name=self.__class__.__name__,
                        modstr="\n".join(
                            "({i}): {m}".format(i=i, m=_indent(repr(m), 2))
                            for i, m in self._children.items()))

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        assert all(not isinstance(cell, BidirectionalCell)
                   for cell in self._children.values())
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        num_cells = len(self._children)
        _, _, batch_size, _ = _format_sequence(length, inputs, layout, None)
        begin_state = _get_begin_state(self, nd, begin_state, inputs,
                                       batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class HybridSequentialRNNCell(HybridRecurrentCell):
    """reference: rnn_cell.py (HybridSequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        return SequentialRNNCell.unroll(
            self, length, inputs, begin_state, layout, merge_outputs,
            valid_length)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def hybrid_forward(self, *args, **kwargs):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    """reference: rnn_cell.py (DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, (int, float)), "rate must be a number"
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, _, _, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if isinstance(inputs, nd.NDArray):
            return self.hybrid_forward(nd, inputs, begin_state or [])
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs,
                              valid_length=valid_length)


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell.
    reference: rnn_cell.py (ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified " \
            "twice" % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError

    def __repr__(self):
        return "{name}({base_cell})".format(name=self.__class__.__name__,
                                            base_cell=self.base_cell)


class ZoneoutCell(ModifierCell):
    """reference: rnn_cell.py (ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout since it doesn't " \
            "support step. Please add ZoneoutCell to the cells underneath " \
            "instead."
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        super().__init__(base_cell)
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self._zoneout_outputs,
                                     self._zoneout_states)
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return nd.invoke("Dropout", nd.ones_like(like), p=p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = nd.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output,
                         prev_output) if p_outputs != 0. else next_output
        new_states = [F.where(mask(p_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if p_states != 0. else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Output = base(x) + x. reference: rnn_cell.py (ResidualCell)."""

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def _alias(self):
        return "residual"

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs, valid_length=valid_length)
        self.base_cell._modified = True
        if isinstance(outputs, list):
            inputs_l, _, _, _ = _format_sequence(length, inputs, layout,
                                                 False)
            outputs = [o + i for o, i in zip(outputs, inputs_l)]
        else:
            inputs_m, _, _, _ = _format_sequence(length, inputs, layout,
                                                 True)
            outputs = outputs + inputs_m
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """reference: rnn_cell.py (BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def __repr__(self):
        return "{name}(forward={l_cell}, backward={r_cell})".format(
            name=self.__class__.__name__,
            l_cell=self._children["l_cell"],
            r_cell=self._children["r_cell"])

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        axis = layout.find("T")
        inputs, _, batch_size, length = _format_sequence(length, inputs,
                                                         layout, False)
        reversed_inputs = list(reversed(inputs))
        begin_state = _get_begin_state(self, nd, begin_state, inputs,
                                       batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False, valid_length=valid_length)
        reversed_r_outputs = list(reversed(r_outputs))
        outputs = [nd.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, reversed_r_outputs)]
        if merge_outputs:
            outputs = nd.concat(*[o.expand_dims(axis) for o in outputs],
                                dim=axis)
        states = l_states + r_states
        return outputs, states
