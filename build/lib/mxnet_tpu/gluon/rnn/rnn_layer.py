"""Fused recurrent layers (LSTM/GRU/RNN) over the fused RNN op.

reference: python/mxnet/gluon/rnn/rnn_layer.py (_RNNLayer via sym.RNN →
cuDNN). Here the fused op is a `lax.scan` kernel (ops/rnn_ops.py); parameter
naming (`l0_i2h_weight`, `r0_h2h_bias`, ...) and layouts (TNC/NTC) match the
reference so checkpoints interchange.
"""
from __future__ import annotations

from ... import ndarray as nd
from ..block import HybridBlock
from . import rnn_cell

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """reference: rnn_layer.py (_RNNLayer)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param("{}{}_i2h_weight".format(j, i),
                                     shape=(ng * nh, ni),
                                     init=i2h_weight_initializer)
                self._register_param("{}{}_h2h_weight".format(j, i),
                                     shape=(ng * nh, nh),
                                     init=h2h_weight_initializer)
                self._register_param("{}{}_i2h_bias".format(j, i),
                                     shape=(ng * nh,),
                                     init=i2h_bias_initializer)
                self._register_param("{}{}_h2h_bias".format(j, i),
                                     shape=(ng * nh,),
                                     init=h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _collect_params_with_prefix(self, prefix=""):
        # reference stores these directly (no children)
        return super()._collect_params_with_prefix(prefix)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _shape_from_input(self, x, *args):
        layout_in = x.shape[2] if self._layout == "TNC" else x.shape[2]
        ni = x.shape[2]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, "{}{}_i2h_weight".format(j, i)).shape = \
                    (ng * nh, ni)
            ni = nh * self._dir

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent state. reference: _RNNLayer.begin_state."""
        if func is None:
            func = nd.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(info.pop("shape", ()), **{
                k: v for k, v in info.items() if k in ("ctx", "dtype")}))
        return states

    def hybrid_forward(self, F, inputs, states=None, **kwargs):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size,
                                      ctx=inputs.context if hasattr(
                                          inputs, "context") else None,
                                      dtype=inputs.dtype)
        if isinstance(states, nd.NDArray):
            states = [states]
        for state, info in zip(states, self.state_info(batch_size)):
            if state.shape != info["shape"]:
                raise ValueError(
                    "Invalid recurrent state shape. Expecting %s, got %s." % (
                        str(info["shape"]), str(state.shape)))
        out = self._forward_kernel(F, inputs, states, **kwargs)
        # out: (output, [state(s)])
        outputs, new_states = out
        if self._layout == "NTC":
            outputs = nd.invoke("swapaxes", outputs, dim1=0, dim2=1) if \
                isinstance(outputs, nd.NDArray) else outputs.swapaxes(0, 1)
        return outputs if skip_states else (outputs, new_states)

    def _flat_params(self, kwargs):
        order = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                order.append(kwargs["{}{}_i2h_weight".format(j, i)])
                order.append(kwargs["{}{}_h2h_weight".format(j, i)])
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                order.append(kwargs["{}{}_i2h_bias".format(j, i)])
                order.append(kwargs["{}{}_h2h_bias".format(j, i)])
        flat = [w.reshape((-1,)) for w in order]
        return nd.concat(*flat, dim=0)

    def _forward_kernel(self, F, inputs, states, **kwargs):
        params = self._flat_params(kwargs)
        if self._mode == "lstm":
            h, c = states
            rnn_out = F.RNN(inputs, params, h, c,
                            state_size=self._hidden_size,
                            num_layers=self._num_layers, mode=self._mode,
                            bidirectional=self._dir == 2, p=self._dropout,
                            state_outputs=True)
            outputs, state_n, cell_n = rnn_out
            return outputs, [state_n, cell_n]
        h = states[0]
        rnn_out = F.RNN(inputs, params, h, None,
                        state_size=self._hidden_size,
                        num_layers=self._num_layers, mode=self._mode,
                        bidirectional=self._dir == 2, p=self._dropout,
                        state_outputs=True)
        outputs, state_n, _ = rnn_out
        return outputs, [state_n]


class RNN(_RNNLayer):
    """Elman RNN layer. reference: rnn_layer.py (RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """LSTM layer. reference: rnn_layer.py (LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", projection_size,
                         **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """GRU layer. reference: rnn_layer.py (GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
