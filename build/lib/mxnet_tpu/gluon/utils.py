"""Gluon utilities. reference: python/mxnet/gluon/utils.py.

`split_and_load` is the reference's single-process data-parallel primitive
(slice a batch across contexts); it remains the eager-mode DP entry point,
while mesh-sharded `pjit` (mxnet_tpu.parallel) is the compiled path.
"""
from __future__ import annotations

import hashlib
import os

import numpy as _np

from .. import ndarray as nd
from ..context import Context

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download", "shape_is_known", "_indent"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray into `num_slice` along batch_axis.
    reference: gluon/utils.py (split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices along "
            "axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data." %
            (str(data.shape), num_slice, batch_axis, num_slice))
    if num_slice == 1:
        return [data]
    step = size // num_slice
    if not even_split:
        slices = []
        for i in range(num_slice):
            begin = i * step
            end = size if i == num_slice - 1 else (i + 1) * step
            slices.append(data.slice_axis(batch_axis, begin, end))
        return slices
    return [data.slice_axis(batch_axis, i * step, (i + 1) * step)
            for i in range(num_slice)]


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split `data` and load each slice on one context.
    reference: gluon/utils.py (split_and_load)."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the sum of their 2-norms is <= max_norm.
    reference: gluon/utils.py (clip_global_norm)."""
    def _norm(array):
        if array.stype == "default":
            x = array.reshape((-1,))
            return nd.invoke("dot", x, x)
        return array.norm().square()
    assert len(arrays) > 0
    ctx = arrays[0].context
    total_norm = nd.invoke("add_n", *[_norm(arr).as_in_context(ctx)
                                      for arr in arrays])
    total_norm = total_norm.sqrt()
    if check_isfinite:
        tn = float(total_norm.asscalar())
        if not _np.isfinite(tn):
            import warnings
            warnings.warn(
                UserWarning("nan or inf is detected. Clipping results will "
                            "be undefined."), stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    scale = nd.invoke("broadcast_minimum", scale,
                      nd.ones((1,), ctx=scale.context))
    for arr in arrays:
        arr *= scale.as_in_context(arr.context)
    if check_isfinite:
        return tn
    return total_norm


def check_sha1(filename, sha1_hash):
    """Check a file against expected sha1. reference: gluon/utils.py."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Download a file. This build has no network egress: resolves only
    file:// URLs and existing local paths; otherwise raises with a clear
    message (reference: gluon/utils.py (download))."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and (
            not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    if url.startswith("file://"):
        import shutil
        shutil.copyfile(url[len("file://"):], fname)
        return fname
    if os.path.exists(url):
        import shutil
        shutil.copyfile(url, fname)
        return fname
    raise RuntimeError(
        "download('%s') requires network access, which this environment "
        "does not have. Place the file at '%s' manually." % (url, fname))


def shape_is_known(shape):
    """Whether a shape is fully known (no 0/None dims)."""
    if shape is None:
        return False
    for dim in shape:
        if not dim:
            return False
    return True


def _indent(s_, num_spaces):
    """Indent a multi-line string (for reprs)."""
    lines = s_.split("\n")
    if len(lines) == 1:
        return s_
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)
