"""Gluon: the imperative neural-network API.
reference: python/mxnet/gluon/__init__.py."""
from . import block
from .block import Block, HybridBlock, SymbolBlock
from .parameter import (Constant, DeferredInitializationError, Parameter,
                        ParameterDict)
from . import nn
from . import rnn
from . import loss
from . import data
from . import utils
from . import model_zoo
from .trainer import Trainer
from . import contrib
from .fused_step import FusedTrainStep

__all__ = ["Block", "HybridBlock", "SymbolBlock", "Constant",
           "DeferredInitializationError", "Parameter", "ParameterDict",
           "Trainer", "FusedTrainStep", "nn", "loss", "utils"]
