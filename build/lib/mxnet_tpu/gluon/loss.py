"""Loss blocks. reference: python/mxnet/gluon/loss.py.

Same classes, weighting (`_apply_weighting`), batch_axis averaging, and
sample_weight broadcast semantics as the reference.
"""
from __future__ import annotations

import numpy as _np

from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "PoissonNLLLoss", "CosineEmbeddingLoss", "SDMLLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """reference: gluon/loss.py (_apply_weighting)."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        assert isinstance(weight, (float, int)), "weight must be a number"
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """Base loss. reference: gluon/loss.py (Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        s = "{name}(batch_axis={_batch_axis}, w={_weight})"
        return s.format(name=self.__class__.__name__, **self.__dict__)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


def _batch_mean(F, loss, batch_axis):
    axes = tuple(i for i in range(loss.ndim) if i != batch_axis)
    return F.mean(loss, axis=axes) if axes else loss


class L2Loss(Loss):
    """0.5*(pred-label)^2. reference: gluon/loss.py (L2Loss)."""

    def __init__(self, weight=1., batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class L1Loss(Loss):
    """|pred-label|. reference: gluon/loss.py (L1Loss)."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE with optional logits input + pos_weight.
    reference: gluon/loss.py (SigmoidBinaryCrossEntropyLoss)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            if pos_weight is None:
                # stable: max(x,0) - x*z + log(1+exp(-|x|))
                loss = F.relu(pred) - pred * label + \
                    F.Activation(F.abs(pred) * -1, act_type="softrelu")
            else:
                log_weight = 1 + F.broadcast_mul(pos_weight - 1, label)
                loss = pred - pred * label + log_weight * (
                    F.Activation(F.abs(pred) * -1, act_type="softrelu") +
                    F.relu(pred * -1))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label +
                         F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label,
                                         pos_weight) +
                         F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """reference: gluon/loss.py (SoftmaxCrossEntropyLoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """reference: gluon/loss.py (KLDivLoss)."""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class CTCLoss(Loss):
    """Connectionist temporal classification.
    reference: gluon/loss.py (CTCLoss) / src/operator/contrib/ctc_loss.cc.
    layout TNC/NTC; labels padded with -1."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        assert layout in ["NTC", "TNC"], \
            "Only 'NTC' and 'TNC' layouts for pred are supported, " \
            "got: %s" % layout
        assert label_layout in ["NT", "TN"], \
            "Only 'NT' and 'TN' layouts for label are supported, " \
            "got: %s" % label_layout
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, 0, 1)   # → TNC
        if self._batch_axis == 1:
            label = F.swapaxes(label, 0, 1)  # → NT
        import jax.numpy as jnp
        import optax
        logits = pred.data_jax if hasattr(pred, "data_jax") else pred
        labels = label.data_jax if hasattr(label, "data_jax") else label
        logits = jnp.transpose(logits, (1, 0, 2))  # TNC → NTC for optax
        T = logits.shape[1]
        N = logits.shape[0]
        if pred_lengths is None:
            logit_pad = jnp.zeros((N, T), dtype=jnp.float32)
        else:
            pl = pred_lengths.data_jax if hasattr(pred_lengths, "data_jax") \
                else pred_lengths
            logit_pad = (jnp.arange(T)[None, :] >= pl[:, None]).astype(
                jnp.float32)
        labels_i = labels.astype(jnp.int32)
        if label_lengths is None:
            label_pad = (labels_i < 0).astype(jnp.float32)
        else:
            ll = label_lengths.data_jax if hasattr(label_lengths, "data_jax") \
                else label_lengths
            L = labels_i.shape[1]
            label_pad = (jnp.arange(L)[None, :] >= ll[:, None]).astype(
                jnp.float32)
        labels_i = jnp.where(labels_i < 0, 0, labels_i)
        # optax expects blank id; reference uses blank=0 ('first')? MXNet CTC
        # blank label is the LAST class by default in gluon (blank_label
        # handling folded: alphabet_size-1). optax uses blank=0; shift.
        from .. import ndarray as nd_mod
        loss = optax.ctc_loss(logits, logit_pad, labels_i, label_pad,
                              blank_id=logits.shape[-1] - 1)
        out = nd_mod.from_jax(loss)
        return _apply_weighting(F, out, self._weight, sample_weight)


class HuberLoss(Loss):
    """Smooth L1. reference: gluon/loss.py (HuberLoss)."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class HingeLoss(Loss):
    """reference: gluon/loss.py (HingeLoss)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class SquaredHingeLoss(Loss):
    """reference: gluon/loss.py (SquaredHingeLoss)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class LogisticLoss(Loss):
    """reference: gluon/loss.py (LogisticLoss)."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format
        if self._label_format not in ["signed", "binary"]:
            raise ValueError(
                "label_format can only be signed or binary, recieved %s."
                % label_format)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(F.abs(pred) * -1, act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)


class TripletLoss(Loss):
    """reference: gluon/loss.py (TripletLoss)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=tuple(i for i in range(pred.ndim)
                                if i != self._batch_axis))
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    """reference: gluon/loss.py (PoissonNLLLoss)."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling_factor = target * F.log(target + 1e-12) - target + \
                0.5 * F.log(2 * target * _np.pi + 1e-12)
            mask = (target > 1).astype(pred.dtype) if hasattr(
                target, "astype") else target > 1
            stirling_factor = stirling_factor * mask
            loss = loss + stirling_factor
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    """reference: gluon/loss.py (CosineEmbeddingLoss)."""

    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        input1 = _reshape_like(F, input1, input2)
        cos_sim = self._cosine_similarity(F, input1, input2)
        label = label.reshape((-1, 1))
        loss = F.where(label == 1, 1 - cos_sim,
                       F.relu(cos_sim - self._margin))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)

    def _cosine_similarity(self, F, x, y, axis=-1):
        x_norm = F.norm(x, axis=axis).reshape((-1, 1))
        y_norm = F.norm(y, axis=axis).reshape((-1, 1))
        x_dot_y = F.sum(x * y, axis=axis).reshape((-1, 1))
        eps_arr = 1e-12
        return x_dot_y / F.broadcast_maximum(
            x_norm * y_norm, F.ones_like(x_norm) * eps_arr)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss over paired batches.
    reference: gluon/loss.py (SDMLLoss) — rows of x1 and x2 are positive
    pairs; every other row is an in-batch negative. The pairwise-distance
    softmax with smoothed targets pulls pairs together without explicit
    negative mining."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._smoothing_parameter = smoothing_parameter

    @staticmethod
    def _pairwise_dist(F, x1, x2):
        # squared euclidean: |a|^2 - 2ab + |b|^2
        a2 = F.sum(x1 * x1, axis=1).reshape((-1, 1))
        b2 = F.sum(x2 * x2, axis=1).reshape((1, -1))
        ab = F.dot(x1, x2.T)
        return F.relu(a2 - 2 * ab + b2)

    def hybrid_forward(self, F, x1, x2, sample_weight=None):
        n = x1.shape[0]
        dist = self._pairwise_dist(F, x1, x2)
        logp = F.log_softmax(-dist, axis=1)
        # smoothed targets: 1-eps on the diagonal pair, eps spread over
        # the in-batch negatives
        eps = self._smoothing_parameter
        eye = F.one_hot(F.arange(0, n), n)
        labels = eye * (1 - eps) + (1 - eye) * (eps / max(n - 1, 1))
        loss = -F.sum(labels * logp, axis=1)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return _batch_mean(F, loss, self._batch_axis)
