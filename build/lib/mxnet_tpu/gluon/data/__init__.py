"""Gluon data API. reference: python/mxnet/gluon/data/__init__.py."""
from .dataset import *  # noqa: F401,F403
from .sampler import *  # noqa: F401,F403
from .dataloader import *  # noqa: F401,F403
from . import vision  # noqa: F401

from . import dataset, sampler, dataloader

__all__ = dataset.__all__ + sampler.__all__ + dataloader.__all__ + ["vision"]
