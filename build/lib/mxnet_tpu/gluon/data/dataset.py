"""Datasets. reference: python/mxnet/gluon/data/dataset.py."""
from __future__ import annotations

import os

from ... import ndarray as nd

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset",
           "_DownloadedDataset"]


class Dataset:
    """Abstract dataset. reference: data/dataset.py (Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        """reference: Dataset.filter."""
        from . import FilterSampler
        return _SampledDataset(self, FilterSampler(fn, self))

    def shard(self, num_shards, index):
        """Shard for distributed data loading (reference: Dataset.shard).
        On a TPU pod each process takes its shard — same contract."""
        assert index < num_shards, \
            "Shard index of out bound: %d out of %d" % (index, num_shards)
        assert num_shards > 0
        assert index >= 0
        length = len(self)
        shard_len = length // num_shards
        rest = length % num_shards
        start = shard_len * index + min(index, rest)
        end = start + shard_len + (index < rest)
        from . import SequentialSampler
        return _SampledDataset(self, _RangeSampler(start, end))

    def take(self, count):
        """reference: Dataset.take."""
        if count is None or count > len(self):
            count = len(self)
        return _SampledDataset(self, _RangeSampler(0, count))

    def sample(self, sampler):
        """reference: Dataset.sample."""
        return _SampledDataset(self, sampler)

    def transform(self, fn, lazy=True):
        """reference: Dataset.transform."""
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        """reference: Dataset.transform_first."""
        return self.transform(_TransformFirstClosure(fn), lazy)


class SimpleDataset(Dataset):
    """Wrap a list/array. reference: data/dataset.py (SimpleDataset)."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _SampledDataset(Dataset):
    def __init__(self, dataset, sampler):
        self._dataset = dataset
        self._sampler = sampler
        self._indices = list(iter(sampler))

    def __len__(self):
        return len(self._sampler)

    def __getitem__(self, idx):
        return self._dataset[self._indices[idx]]


class _RangeSampler:
    def __init__(self, start, end):
        self._start = start
        self._end = end

    def __iter__(self):
        return iter(range(self._start, self._end))

    def __len__(self):
        return self._end - self._start


class ArrayDataset(Dataset):
    """Zip of arrays. reference: data/dataset.py (ArrayDataset)."""

    def __init__(self, *args):
        assert len(args) > 0, "Needs at least 1 arrays"
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, \
                "All arrays must have the same length; array[0] has length " \
                "%d while array[%d] has %d." % (self._length, i + 1,
                                                len(data))
            if isinstance(data, nd.NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(data[idx] for data in self._data)

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file. reference: data/dataset.py
    (RecordFileDataset) over dmlc::RecordIOReader."""

    def __init__(self, filename):
        self.idx_file = os.path.splitext(filename)[0] + ".idx"
        self.filename = filename
        from ...recordio import IndexedRecordIO
        self._record = IndexedRecordIO(self.idx_file, self.filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    def __len__(self):
        return len(self._record.keys)


class _DownloadedDataset(Dataset):
    """Base for MNIST/CIFAR-style datasets kept in a root dir.
    reference: data/dataset.py (_DownloadedDataset). This build has no
    network egress: `_get_data` implementations read local files and fall
    back to deterministic synthetic data when absent (documented)."""

    def __init__(self, root, transform):
        super().__init__()
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError
