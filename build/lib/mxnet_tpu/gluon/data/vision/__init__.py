"""Vision data API. reference: python/mxnet/gluon/data/vision/__init__.py."""
from .datasets import *  # noqa: F401,F403
from . import transforms  # noqa: F401
from . import datasets

__all__ = datasets.__all__ + ["transforms"]
