"""Vision datasets. reference: python/mxnet/gluon/data/vision/datasets.py.

MNIST/FashionMNIST read the standard idx files, CIFAR10/100 the standard
binary batches — byte-compatible with the reference's expectations. This
environment has no network egress, so when files are absent each dataset
falls back to a DETERMINISTIC synthetic sample set (seeded per class) of the
same shapes/dtypes — sufficient for training-pipeline and perf work; drop
the real files into `root` to train on actual data.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as _np

from .... import ndarray as nd
from ..dataset import ArrayDataset, Dataset, _DownloadedDataset
from ....recordio import unpack as rec_unpack

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


def _synthetic_images(num, shape, num_classes, seed):
    """Deterministic class-structured synthetic data: each class is a fixed
    random template plus noise, so classifiers can actually learn."""
    rng = _np.random.RandomState(seed)
    templates = rng.randint(0, 255, size=(num_classes,) + shape)
    labels = rng.randint(0, num_classes, size=(num,))
    noise = rng.randint(-40, 40, size=(num,) + shape)
    data = _np.clip(templates[labels] + noise, 0, 255).astype("uint8")
    return data, labels.astype("int32")


class MNIST(_DownloadedDataset):
    """MNIST (idx format). reference: vision/datasets.py (MNIST)."""

    _TRAIN = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _TEST = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")
    _SHAPE = (28, 28, 1)
    _CLASSES = 10
    _SYN_COUNT = (8192, 1024)

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        img_file, lbl_file = self._TRAIN if self._train else self._TEST
        img_path = os.path.join(self._root, img_file)
        lbl_path = os.path.join(self._root, lbl_file)
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            with gzip.open(lbl_path, "rb") as fin:
                struct.unpack(">II", fin.read(8))
                label = _np.frombuffer(fin.read(), dtype=_np.uint8) \
                    .astype(_np.int32)
            with gzip.open(img_path, "rb") as fin:
                struct.unpack(">IIII", fin.read(16))
                data = _np.frombuffer(fin.read(), dtype=_np.uint8)
                data = data.reshape(len(label), 28, 28, 1)
        else:
            n = self._SYN_COUNT[0] if self._train else self._SYN_COUNT[1]
            data, label = _synthetic_images(n, self._SHAPE, self._CLASSES,
                                            seed=42 if self._train else 43)
        self._data = nd.array(data, dtype=data.dtype)
        self._label = label


class FashionMNIST(MNIST):
    """reference: vision/datasets.py (FashionMNIST)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root=root, train=train, transform=transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 (binary batches). reference: vision/datasets.py (CIFAR10)."""

    _SHAPE = (32, 32, 3)
    _CLASSES = 10
    _SYN_COUNT = (8192, 1024)

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        self._archive_file_name = "cifar-10-binary.tar.gz"
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = _np.frombuffer(fin.read(), dtype=_np.uint8).reshape(
                -1, 3072 + 1)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(_np.int32)

    def _get_data(self):
        if self._train:
            filename = [os.path.join(self._root,
                                     "data_batch_%d.bin" % (i + 1))
                        for i in range(5)]
        else:
            filename = [os.path.join(self._root, "test_batch.bin")]
        if all(os.path.exists(f) for f in filename):
            data, label = zip(*(self._read_batch(f) for f in filename))
            data = _np.concatenate(data)
            label = _np.concatenate(label)
        else:
            n = self._SYN_COUNT[0] if self._train else self._SYN_COUNT[1]
            data, label = _synthetic_images(n, self._SHAPE, self._CLASSES,
                                            seed=44 if self._train else 45)
        self._data = nd.array(data, dtype=data.dtype)
        self._label = label


class CIFAR100(CIFAR10):
    """reference: vision/datasets.py (CIFAR100)."""

    _CLASSES = 100

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"), fine_label=False,
                 train=True, transform=None):
        self._fine_label = fine_label
        super().__init__(root=root, train=train, transform=transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = _np.frombuffer(fin.read(), dtype=_np.uint8).reshape(
                -1, 3072 + 2)
        return data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0 + self._fine_label].astype(_np.int32)

    def _get_data(self):
        if self._train:
            filename = [os.path.join(self._root, "train.bin")]
        else:
            filename = [os.path.join(self._root, "test.bin")]
        if all(os.path.exists(f) for f in filename):
            data, label = zip(*(self._read_batch(f) for f in filename))
            data = _np.concatenate(data)
            label = _np.concatenate(label)
        else:
            n = self._SYN_COUNT[0] if self._train else self._SYN_COUNT[1]
            data, label = _synthetic_images(n, self._SHAPE, self._CLASSES,
                                            seed=46 if self._train else 47)
        self._data = nd.array(data, dtype=data.dtype)
        self._label = label


class ImageRecordDataset(Dataset):
    """Dataset over a .rec image record file.
    reference: vision/datasets.py (ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        from ..dataset import RecordFileDataset
        self._record = RecordFileDataset(filename)
        self._flag = flag
        self._transform = transform

    def __len__(self):
        return len(self._record)

    def __getitem__(self, idx):
        from ....image import imdecode
        record = self._record[idx]
        header, img = rec_unpack(record)
        if self._transform is not None:
            return self._transform(imdecode(img, self._flag), header.label)
        return imdecode(img, self._flag), header.label


class ImageFolderDataset(Dataset):
    """root/category/image.jpg layout.
    reference: vision/datasets.py (ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png", ".npy"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        from ....image import imread
        if self.items[idx][0].endswith(".npy"):
            img = nd.array(_np.load(self.items[idx][0]))
        else:
            img = imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
