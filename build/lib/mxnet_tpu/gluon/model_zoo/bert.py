"""BERT through the Gluon API — the NLP model family the reference served
via external GluonNLP (`gluonnlp.model.BERTModel`), built on the same fused
self-attention op surface the reference exposed for it
(reference: src/operator/contrib/transformer.cc —
`_contrib_interleaved_matmul_selfatt_qk` / `_valatt`; GluonNLP's
BERTEncoder consumed exactly these ops in TNC layout).

The functional twin lives in `mxnet_tpu/models/bert.py` (drives the
`BENCH=bert` headline); this module is the user-facing HybridBlock stack:
hybridize() compiles each block through the CachedOp≙jax.jit path, and the
whole model works with `gluon.Trainer`/`FusedTrainStep`.

Layout note (TPU-first): the encoder runs in TNC (seq, batch, units) like
GluonNLP's, so the fused attention ops batch their matmuls on the MXU with
no per-layer transposes; the only NTC↔TNC transposes are at the embedding
and output boundaries, which XLA folds into neighbouring ops.
"""
from __future__ import annotations

from ..block import HybridBlock
from .. import nn

__all__ = ["BERTEncoderCell", "BERTEncoder", "BERTModel",
           "bert_12_768_12", "bert_24_1024_16", "get_bert_model"]


class BERTEncoderCell(HybridBlock):
    """One transformer encoder layer: fused self-attention + FFN with
    post-layernorm residuals (reference: GluonNLP BERTEncoderCell)."""

    def __init__(self, units=768, hidden_size=3072, num_heads=12,
                 dropout=0.1, layer_norm_eps=1e-12, **kwargs):
        super().__init__(**kwargs)
        self._num_heads = num_heads
        with self.name_scope():
            self.attention_qkv = nn.Dense(3 * units, flatten=False,
                                          prefix="qkv_")
            self.attention_proj = nn.Dense(units, flatten=False,
                                           prefix="proj_")
            self.attention_dropout = nn.Dropout(dropout)
            self.layer_norm = nn.LayerNorm(epsilon=layer_norm_eps,
                                           prefix="ln1_")
            self.ffn_1 = nn.Dense(hidden_size, flatten=False, prefix="ffn1_")
            self.activation = nn.GELU()
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.dropout_layer = nn.Dropout(dropout)
            self.ffn_layer_norm = nn.LayerNorm(epsilon=layer_norm_eps,
                                               prefix="ln2_")

    def hybrid_forward(self, F, x, mask=None):
        # x: (seq, batch, units); mask: additive (batch*heads, seq, seq)
        qkv = self.attention_qkv(x)
        scores = F.contrib.interleaved_matmul_selfatt_qk(
            qkv, heads=self._num_heads)
        if mask is not None:
            scores = scores + mask
        att = F.softmax(scores, axis=-1)
        att = self.attention_dropout(att)
        out = F.contrib.interleaved_matmul_selfatt_valatt(
            qkv, att, heads=self._num_heads)
        x = self.layer_norm(x + self.dropout_layer(
            self.attention_proj(out)))
        y = self.ffn_2(self.activation(self.ffn_1(x)))
        return self.ffn_layer_norm(x + self.dropout_layer(y))


class BERTEncoder(HybridBlock):
    """Embedding sum (word + position + token-type) + N encoder cells.
    reference: GluonNLP BERTEncoder / BERTModel embedding stack."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 token_type_vocab_size=2, dropout=0.1,
                 layer_norm_eps=1e-12, **kwargs):
        super().__init__(**kwargs)
        self._num_heads = num_heads
        self._max_length = max_length
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(token_type_vocab_size,
                                                 units,
                                                 prefix="token_type_embed_")
            # init=None: defer to the initializer the user passes to
            # net.initialize() — a pinned init here would silently zero the
            # positional signal (GluonNLP applies the model initializer)
            self.position_weight = self.params.get(
                "position_weight", shape=(max_length, units))
            self.embed_layer_norm = nn.LayerNorm(epsilon=layer_norm_eps,
                                                 prefix="embed_ln_")
            self.embed_dropout = nn.Dropout(dropout)
            self.transformer_cells = []
            for i in range(num_layers):
                cell = BERTEncoderCell(units=units, hidden_size=hidden_size,
                                       num_heads=num_heads, dropout=dropout,
                                       layer_norm_eps=layer_norm_eps,
                                       prefix="layer%d_" % i)
                self.register_child(cell)
                self.transformer_cells.append(cell)

    def _length_mask(self, F, inputs, valid_length):
        """(batch,) valid lengths -> additive mask (batch*heads, seq, seq)
        with -1e9 on the padded key positions."""
        seq = inputs.shape[1]
        steps = F.arange(seq)
        # (batch, seq): 1 where the key position is valid
        valid = F.broadcast_lesser(
            steps.reshape((1, -1)), valid_length.reshape((-1, 1)))
        neg = (1.0 - valid) * -1e9
        # broadcast over heads and the query axis
        mask = neg.reshape((-1, 1, 1, seq)).broadcast_to(
            (valid_length.shape[0], self._num_heads, seq, seq))
        return mask.reshape((-3, 0, 0))

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None,
                       position_weight=None):
        # inputs: (batch, seq) token ids
        seq = inputs.shape[1]
        x = self.word_embed(inputs)
        if token_types is None:
            token_types = F.zeros_like(inputs)
        x = x + self.token_type_embed(token_types)
        pos = F.slice_axis(position_weight, axis=0, begin=0, end=seq)
        x = x + pos.reshape((1, seq, -1))
        x = self.embed_dropout(self.embed_layer_norm(x))
        mask = (None if valid_length is None
                else self._length_mask(F, inputs, valid_length))
        x = F.transpose(x, axes=(1, 0, 2))   # NTC -> TNC
        for cell in self.transformer_cells:
            x = cell(x, mask) if mask is not None else cell(x)
        return F.transpose(x, axes=(1, 0, 2))  # TNC -> NTC


class BERTModel(HybridBlock):
    """Encoder + pooler + masked-LM decoder + next-sentence classifier.
    reference: GluonNLP BERTModel (word_embed/encoder/pooler/decoder)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 token_type_vocab_size=2, dropout=0.1, use_pooler=True,
                 use_decoder=True, use_classifier=True, **kwargs):
        super().__init__(**kwargs)
        if use_classifier and not use_pooler:
            # same contract as GluonNLP's BERTModel: the NSP head consumes
            # the pooled [CLS] vector
            raise ValueError("BERTModel: use_classifier=True requires "
                             "use_pooler=True (pass use_classifier=False)")
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        with self.name_scope():
            self.encoder = BERTEncoder(
                vocab_size=vocab_size, units=units, hidden_size=hidden_size,
                num_layers=num_layers, num_heads=num_heads,
                max_length=max_length,
                token_type_vocab_size=token_type_vocab_size,
                dropout=dropout, prefix="encoder_")
            if use_pooler:
                self.pooler = nn.Dense(units, activation="tanh",
                                       flatten=False, prefix="pooler_")
            if use_decoder:
                # MLM head: transform + layernorm + vocab projection
                self.decoder = nn.HybridSequential(prefix="decoder_")
                with self.decoder.name_scope():
                    self.decoder.add(nn.Dense(units, flatten=False))
                    self.decoder.add(nn.GELU())
                    self.decoder.add(nn.LayerNorm(epsilon=1e-12))
                    self.decoder.add(nn.Dense(vocab_size, flatten=False))
            if use_classifier:
                self.classifier = nn.Dense(2, flatten=False,
                                           prefix="nsp_")

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        """Returns (sequence_output[, pooled][, nsp_logits][, mlm_logits])
        in GluonNLP's order: encoder output always first."""
        seq_out = self.encoder(inputs, token_types, valid_length)
        outputs = [seq_out]
        pooled = None
        if self._use_pooler:
            cls = F.slice_axis(seq_out, axis=1, begin=0, end=1)
            pooled = self.pooler(cls.reshape((0, -1)))
            outputs.append(pooled)
        if self._use_classifier and pooled is not None:
            outputs.append(self.classifier(pooled))
        if self._use_decoder:
            outputs.append(self.decoder(seq_out))
        return outputs[0] if len(outputs) == 1 else tuple(outputs)


def get_bert_model(model_name="bert_12_768_12", vocab_size=30522,
                   dropout=0.1, **kwargs):
    """reference: gluonnlp.model.get_model names — bert_{L}_{H}_{A}."""
    presets = {
        "bert_12_768_12": dict(units=768, hidden_size=3072, num_layers=12,
                               num_heads=12),
        "bert_24_1024_16": dict(units=1024, hidden_size=4096, num_layers=24,
                                num_heads=16),
    }
    if model_name not in presets:
        raise ValueError("unknown BERT preset %r (have %s)"
                         % (model_name, sorted(presets)))
    cfg = dict(presets[model_name])
    cfg.update(kwargs)
    return BERTModel(vocab_size=vocab_size, dropout=dropout, **cfg)


def bert_12_768_12(**kwargs):
    """BERT-base. reference: gluonnlp model name bert_12_768_12."""
    return get_bert_model("bert_12_768_12", **kwargs)


def bert_24_1024_16(**kwargs):
    """BERT-large. reference: gluonnlp model name bert_24_1024_16."""
    return get_bert_model("bert_24_1024_16", **kwargs)
