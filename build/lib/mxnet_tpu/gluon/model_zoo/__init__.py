"""Model zoo. reference: python/mxnet/gluon/model_zoo/__init__.py (vision)
+ the BERT family the reference ecosystem served through GluonNLP."""
from . import vision
from . import bert
from .vision import get_model

__all__ = ["vision", "bert", "get_model"]
