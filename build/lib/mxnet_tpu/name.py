"""Automatic naming. reference: python/mxnet/name.py (NameManager, Prefix).

Thread-local manager stack generating unique names like `dense0`, `conv1_`;
used by both Gluon block prefixes and Symbol node naming.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class _Current(threading.local):
    def __init__(self):
        self.value = None


class NameManager:
    """reference: python/mxnet/name.py (NameManager)."""

    _current = _Current()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        """Return `name` if given, else generate `hint%d`."""
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        if NameManager._current.value is None:
            NameManager._current.value = NameManager()
        self._old_manager = NameManager._current.value
        NameManager._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_manager
        NameManager._current.value = self._old_manager


class Prefix(NameManager):
    """Manager that prepends a prefix to every name.
    reference: python/mxnet/name.py (Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


# expose a class-level accessor mirroring the reference's
# `NameManager.current` property usage
class _CurrentAccessor:
    def get(self, name, hint):
        cur = NameManager._current.value
        if cur is None:
            cur = NameManager._current.value = NameManager()
        return cur.get(name, hint)


NameManager.current = _CurrentAccessor()
