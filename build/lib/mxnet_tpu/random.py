"""Stateful RNG facade over JAX's functional threefry keys.

TPU-native analog of the reference's per-device `RandGenerator<xpu>`
(reference: src/common/random_generator.h, include/mxnet/random_generator.h,
seeded via python/mxnet/random.py (seed)). The reference keeps mutable
Philox/MT state per device; here a per-context key table holds a threefry key
that is split on every draw, preserving `mx.random.seed(s[, ctx])` semantics
while staying functional underneath (each op consumes a fresh subkey).
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "take_key", "fold_in", "Generator"]

_state = threading.local()
_DEFAULT_SEED = 0


def _table():
    if not hasattr(_state, "keys"):
        _state.keys = {}
    return _state.keys


def seed(seed_state, ctx="all"):
    """Seed the RNG. reference: python/mxnet/random.py (seed) — seeds every
    device generator, or one device when ctx is given."""
    if ctx == "all":
        _table().clear()
        global _DEFAULT_SEED
        _DEFAULT_SEED = int(seed_state)
        _table()[None] = jax.random.key(int(seed_state))
    else:
        key = (ctx.device_type, ctx.device_id)
        _table()[key] = jax.random.key(int(seed_state))


def push_trace_key(key):
    """Enter a functional-RNG scope: while active, `take_key` splits from
    `key` (a traced jax key) instead of the global table. Used by CachedOp /
    hybridize so random ops inside a jit trace consume a per-call key input
    rather than baking a constant (reference analog: per-op kRandom resource
    requests, src/resource.cc)."""
    if not hasattr(_state, "trace_keys"):
        _state.trace_keys = []
    _state.trace_keys.append(key)


def pop_trace_key():
    return _state.trace_keys.pop()


def take_key(ctx=None):
    """Split the current key and return a fresh subkey (advances state)."""
    if getattr(_state, "trace_keys", None):
        k0, k1 = jax.random.split(_state.trace_keys[-1])
        _state.trace_keys[-1] = k0
        return k1
    tbl = _table()
    key = None if ctx is None else (ctx.device_type, ctx.device_id)
    if key not in tbl:
        if key is not None and None in tbl:
            # derive device stream from the global seed, like the reference's
            # per-device generators seeded from one seed + device id.
            # NB: stable hash — python's hash() is salted per process and
            # would break cross-process seed determinism
            import zlib
            stable = zlib.crc32(key[0].encode()) ^ (key[1] & 0xFFFF)
            tbl[key] = jax.random.fold_in(tbl[None], stable & 0x7FFFFFFF)
        else:
            tbl[key] = jax.random.key(_DEFAULT_SEED)
    k0, k1 = jax.random.split(tbl[key])
    tbl[key] = k0
    return k1


def fold_in(data):
    """Deterministically derive a key from current state + integer data."""
    return jax.random.fold_in(take_key(), int(data))


def _nd_random(op):
    def fn(*args, **kwargs):
        from . import ndarray as _nd
        return _nd.invoke(op, *args, **kwargs)
    fn.__name__ = op.lstrip("_")
    return fn


# sampling entry points (reference: python/mxnet/random.py delegates to
# mx.nd.random.*)
uniform = _nd_random("_random_uniform")
normal = _nd_random("_random_normal")
randn = _nd_random("_random_normal")
randint = _nd_random("_random_randint")
gamma = _nd_random("_random_gamma")
exponential = _nd_random("_random_exponential")
poisson = _nd_random("_random_poisson")
negative_binomial = _nd_random("_random_negative_binomial")
generalized_negative_binomial = _nd_random(
    "_random_generalized_negative_binomial")
multinomial = _nd_random("_sample_multinomial")
shuffle = _nd_random("_shuffle")


class Generator:
    """Explicit generator object for code that wants owned RNG state."""

    def __init__(self, seed_state=0):
        self._key = jax.random.key(int(seed_state))

    def take_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub
