"""`mx.viz` — network visualization.

reference: python/mxnet/visualization.py (print_summary, plot_network).
print_summary walks the symbol JSON; plot_network needs graphviz, which
this image does not ship — it raises with a pointer (same failure mode the
reference has without the optional dependency).
"""
from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-by-layer table of a symbol graph (reference: print_summary)."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = set(h[0] for h in conf.get("heads", []))

    shape_dict = {}
    out_shape_dict = {}
    data_names = set(shape or ())
    if shape is not None:
        arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
        for name, s in zip(symbol.list_arguments(), arg_shapes):
            shape_dict[name] = s
        try:  # per-node output shapes via the internals view
            ints = symbol.get_internals()
            _, int_shapes, _ = ints.infer_shape(**shape)
            for oname, s in zip(ints.list_outputs(), int_shapes):
                out_shape_dict[oname] = s
                if oname.endswith("_output0"):
                    out_shape_dict[oname[:-len("_output0")]] = s
        except Exception:
            pass

    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(vals):
        line = ""
        for v, p in zip(vals, positions):
            line = (line + str(v))[:p - 1].ljust(p)
        print(line)

    print("_" * line_length)
    print_row(fields)
    print("=" * line_length)
    total_params = 0
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null" and i not in heads and name not in shape_dict:
            continue
        prev = ", ".join(nodes[int(a[0])]["name"] for a in node["inputs"][:3])
        out_shape = shape_dict.get(name) or out_shape_dict.get(name, "")
        params = 0
        if op != "null":
            # parameters = null inputs whose shapes were INFERRED (anything
            # the caller named in `shape` is a data input, reference
            # convention)
            for a in node["inputs"]:
                in_node = nodes[int(a[0])]
                pname = in_node["name"]
                if in_node["op"] == "null" and pname in shape_dict and \
                        pname not in data_names:
                    n = 1
                    for d in shape_dict[pname]:
                        n *= d
                    params += n
        total_params += params
        print_row(["%s (%s)" % (name, op), out_shape, params, prev])
    print("=" * line_length)
    print("Total params: %d" % total_params)
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    raise ImportError(
        "plot_network requires the optional graphviz package, which is not "
        "available in this environment; use mx.viz.print_summary instead")
