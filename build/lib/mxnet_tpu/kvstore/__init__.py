"""KVStore package. reference: python/mxnet/kvstore/__init__.py."""
from .kvstore import KVStore, KVStoreLocal, create

__all__ = ["KVStore", "KVStoreLocal", "create"]
