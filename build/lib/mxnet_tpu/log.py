"""`mx.log` — logging helpers (reference: python/mxnet/log.py)."""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger"]

_LOG_FMT = "%(asctime)s [%(levelname)s] %(name)s %(message)s"
_DATE_FMT = "%m%d %H:%M:%S"


def get_logger(name=None, filename=None, filemode=None, level=logging.WARNING):
    """reference: log.get_logger — logger with the mxnet format."""
    logger = logging.getLogger(name)
    if getattr(logger, "_init_done", False):
        logger.setLevel(level)
        return logger
    logger._init_done = True
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_LOG_FMT, _DATE_FMT))
    logger.addHandler(handler)
    logger.setLevel(level)
    return logger
