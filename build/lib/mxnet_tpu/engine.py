"""`mx.engine` — execution-engine controls.

reference: python/mxnet/engine.py (bulk, set_bulk_size): batches engine
pushes into bulked segments. Under XLA the analog is a no-op-with-truth:
dispatch is already fully async and fusion happens in the compiler, so the
bulk size is recorded for API compat and `bulk()` remains a valid scope.
"""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

_BULK_SIZE = 15  # the reference default (MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN)


def set_bulk_size(size):
    """reference: engine.set_bulk_size — returns the previous size."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


@contextlib.contextmanager
def bulk(size):
    """reference: engine.bulk — scope with a different bulk size."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
