"""Detection image iterator + label-aware augmenters.

reference: python/mxnet/image/detection.py — `ImageDetIter`,
`CreateDetAugmenter`, and the `Det*Aug` family. Labels ride the
reference's packed .lst/.rec format: ``[A, B, obj0..objN]`` where A is the
header width (extra header fields skipped), B the per-object width, and
each object is ``[id, xmin, ymin, xmax, ymax, ...]`` with coordinates
normalized to [0, 1]. The iterator emits labels as a dense
``(batch, max_objects, B)`` tensor padded with -1 rows — exactly what
`MultiBoxTarget` consumes.
"""
from __future__ import annotations

import random

import numpy as _np

from . import ndarray as nd
from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    HueJitterAug, LightingAug, RandomGrayAug, ResizeAug,
                    ForceResizeAug, ImageIter, imresize, fixed_crop)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter base: __call__(src, label) -> (src, label).
    reference: detection.py (DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return [self.__class__.__name__.lower(), self._kwargs]

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter into the detection chain (labels pass
    through untouched). reference: detection.py (DetBorrowAug)."""

    def __init__(self, augmenter):
        assert isinstance(augmenter, Augmenter)
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one augmenter from a list (or skip with skip_prob).
    reference: detection.py (DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if random.random() < self.skip_prob or not self.aug_list:
            return src, label
        return random.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and flip box x-coordinates with probability p.
    reference: detection.py (DetHorizontalFlipAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if random.random() < self.p:
            src = nd.array(_np.ascontiguousarray(
                src.asnumpy()[:, ::-1, :]), dtype=src.dtype)
            label = label.copy()
            valid = label[:, 0] >= 0
            x1 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x1
        return src, label


def _box_area(boxes):
    return _np.maximum(boxes[:, 2] - boxes[:, 0], 0) * \
        _np.maximum(boxes[:, 3] - boxes[:, 1], 0)


class DetRandomCropAug(DetAugmenter):
    """Random crop constrained by object coverage (SSD-style).
    reference: detection.py (DetRandomCropAug)."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 min_eject_coverage=0.3, max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _update_labels(self, label, crop, height, width):
        """Crop (x0, y0, w, h) in pixels -> updated normalized labels, or
        None if every object is ejected."""
        x0, y0, cw, ch = crop
        out = label.copy()
        valid_rows = []
        for i in range(out.shape[0]):
            if out[i, 0] < 0:
                continue
            # to pixels
            x1 = out[i, 1] * width
            y1 = out[i, 2] * height
            x2 = out[i, 3] * width
            y2 = out[i, 4] * height
            area = max(x2 - x1, 0) * max(y2 - y1, 0)
            nx1, ny1 = max(x1, x0), max(y1, y0)
            nx2, ny2 = min(x2, x0 + cw), min(y2, y0 + ch)
            inter = max(nx2 - nx1, 0) * max(ny2 - ny1, 0)
            if area <= 0 or inter / area < self.min_eject_coverage:
                continue
            out[i, 1] = (nx1 - x0) / cw
            out[i, 2] = (ny1 - y0) / ch
            out[i, 3] = (nx2 - x0) / cw
            out[i, 4] = (ny2 - y0) / ch
            valid_rows.append(i)
        if not valid_rows:
            return None
        kept = out[valid_rows]
        pad = _np.full_like(out, -1.0)
        pad[:len(valid_rows)] = kept
        return pad

    def __call__(self, src, label):
        height, width = src.shape[0], src.shape[1]
        for _ in range(self.max_attempts):
            area_frac = random.uniform(*self.area_range)
            ratio = random.uniform(*self.aspect_ratio_range)
            ch = int(round((area_frac * height * width / ratio) ** 0.5))
            cw = int(round(ch * ratio))
            if ch <= 0 or cw <= 0 or ch > height or cw > width:
                continue
            y0 = random.randint(0, height - ch)
            x0 = random.randint(0, width - cw)
            # coverage check against the best-covered object
            valid = label[:, 0] >= 0
            if valid.any():
                bx = label[valid, 1:5] * [width, height, width, height]
                ix1 = _np.maximum(bx[:, 0], x0)
                iy1 = _np.maximum(bx[:, 1], y0)
                ix2 = _np.minimum(bx[:, 2], x0 + cw)
                iy2 = _np.minimum(bx[:, 3], y0 + ch)
                inter = _np.maximum(ix2 - ix1, 0) * _np.maximum(
                    iy2 - iy1, 0)
                area = _box_area(bx)
                cov = _np.where(area > 0, inter / _np.maximum(area, 1e-12),
                                0.0)
                # reference _check_satisfy_constraints: every object the
                # crop OVERLAPS must reach the coverage floor; objects the
                # crop excludes entirely (cov == 0) are allowed here and
                # ejected from the label by min_eject_coverage below
                touched = cov[cov > 0]
                if touched.size == 0 or \
                        touched.min() < self.min_object_covered:
                    continue
            new_label = self._update_labels(label, (x0, y0, cw, ch),
                                            height, width)
            if new_label is None:
                continue
            return fixed_crop(src, x0, y0, cw, ch), new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Random expansion pad (zoom-out) with label rescale.
    reference: detection.py (DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        height, width = src.shape[0], src.shape[1]
        for _ in range(self.max_attempts):
            scale = random.uniform(*self.area_range)
            ratio = random.uniform(*self.aspect_ratio_range)
            if scale < 1.0:
                continue
            nh = int(round((scale * height * width / ratio) ** 0.5))
            nw = int(round(nh * ratio))
            if nh < height or nw < width:
                continue
            y0 = random.randint(0, nh - height)
            x0 = random.randint(0, nw - width)
            img = src.asnumpy()
            canvas = _np.empty((nh, nw, img.shape[2]), img.dtype)
            canvas[...] = _np.asarray(self.pad_val, img.dtype)
            canvas[y0:y0 + height, x0:x0 + width] = img
            out = label.copy()
            valid = out[:, 0] >= 0
            out[valid, 1] = (out[valid, 1] * width + x0) / nw
            out[valid, 2] = (out[valid, 2] * height + y0) / nh
            out[valid, 3] = (out[valid, 3] * width + x0) / nw
            out[valid, 4] = (out[valid, 4] * height + y0) / nh
            return nd.array(canvas, dtype=src.dtype), out
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmenter chain.
    reference: detection.py (CreateDetAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (min(area_range[0], 1.0),
                                 min(area_range[1], 1.0)),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0),
                               max(area_range[1], 1.0)),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # force to the consumer shape AFTER geometry augs
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.814],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = _np.asarray(mean)
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = _np.asarray(std)
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: packed det labels -> dense padded label tensor.
    reference: detection.py (ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", imglist=None,
                 aug_list=None, data_name="data", label_name="label",
                 last_batch_handle="pad", **kwargs):
        det_kwargs = {}
        for k in ("resize", "rand_crop", "rand_pad", "rand_gray",
                  "rand_mirror", "mean", "std", "brightness", "contrast",
                  "saturation", "pca_noise", "hue", "inter_method",
                  "min_object_covered", "aspect_ratio_range", "area_range",
                  "min_eject_coverage", "max_attempts", "pad_val"):
            if k in kwargs:
                det_kwargs[k] = kwargs.pop(k)
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **det_kwargs)
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, imglist=imglist,
                         aug_list=[],   # det augs run in next(), label-aware
                         data_name=data_name, label_name=label_name,
                         label_width=-1 if "label_width" not in kwargs
                         else kwargs.pop("label_width"), **kwargs)
        self.det_auglist = aug_list
        self._label_shape = None
        # first pass: find max object count to fix the padded label shape
        self.max_objects, self.obj_width = self._estimate_label_shape()
        from .io.io import DataDesc
        self.provide_label = [DataDesc(
            label_name, (batch_size, self.max_objects, self.obj_width))]

    # -- packed label [A, B, objs...] -> (num_obj, B) normalized ----------
    @staticmethod
    def _parse_label(raw):
        raw = _np.asarray(raw, _np.float32).ravel()
        if raw.size < 2:
            raise ValueError("invalid det label: needs [A, B, ...] header")
        a, b = int(raw[0]), int(raw[1])
        if b < 5:
            raise ValueError("invalid det label: object width %d < 5" % b)
        objs = raw[a:]
        n = objs.size // b
        return objs[:n * b].reshape(n, b).copy()

    def _next_label(self):
        """Label of the next sample WITHOUT decoding its image — a
        construction-time scan over a big .rec must not pay the decode."""
        return self.next_sample(decode=False)[0]

    def _estimate_label_shape(self):
        max_n, width = 0, 5
        self.reset()
        try:
            while True:
                parsed = self._parse_label(self._next_label())
                max_n = max(max_n, parsed.shape[0])
                width = max(width, parsed.shape[1])
        except StopIteration:
            pass
        self.reset()
        return max(max_n, 1), width

    def next(self):
        from .io.io import DataBatch
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = _np.zeros((batch_size, h, w, c), dtype="float32")
        batch_label = _np.full(
            (batch_size, self.max_objects, self.obj_width), -1.0, "float32")
        i = 0
        pad = 0
        try:
            while i < batch_size:
                raw_label, data = self.next_sample()
                label = self._parse_label(raw_label)
                full = _np.full((self.max_objects, self.obj_width), -1.0,
                                _np.float32)
                full[:label.shape[0], :label.shape[1]] = \
                    label[:self.max_objects]
                for aug in self.det_auglist:
                    data, full = aug(data, full)
                batch_data[i] = data.asnumpy() if isinstance(
                    data, nd.NDArray) else data
                batch_label[i] = full
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = batch_size - i
            for j in range(i, batch_size):
                batch_data[j] = batch_data[j % max(i, 1)]
                batch_label[j] = batch_label[j % max(i, 1)]
        data_nchw = _np.transpose(batch_data, (0, 3, 1, 2))
        return DataBatch([nd.array(data_nchw, dtype=self.dtype)],
                         [nd.array(batch_label)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
