"""`mx.npx` — numpy-extension namespace. reference:
python/mxnet/numpy_extension/ — operators outside the numpy standard
(neural-net ops, np-mode switches) for use with mx.np arrays. Every
function rides an existing registry op, so it works identically on
`mx.np.ndarray` and legacy `mx.nd.NDArray` inputs, records on the
autograd tape, and traces under `hybridize()`."""
from __future__ import annotations

from .ndarray.ndarray import invoke as _raw_invoke
from .numpy.multiarray import as_np_ndarray as _as_np


def invoke(*args, **kwargs):
    return _as_np(_raw_invoke(*args, **kwargs))

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "softmax", "log_softmax", "masked_softmax", "relu", "sigmoid",
           "one_hot", "pick", "topk", "batch_dot", "embedding", "gamma",
           "activation", "fully_connected", "convolution", "deconvolution",
           "pooling", "batch_norm", "layer_norm", "group_norm", "dropout",
           "leaky_relu", "rnn", "reshape_like", "arange_like",
           "broadcast_like", "gather_nd", "scatter_nd", "smooth_l1",
           "sequence_mask", "erf", "erfinv", "seed", "waitall", "save",
           "load", "cast"]

_np_mode = {"array": False, "shape": False}


def set_np(shape=True, array=True):
    """reference: npx.set_np — enables numpy semantics globally. The TPU
    build's arrays are numpy-semantic already (jax.numpy underneath), so
    this only records the flags for is_np_* queries."""
    _np_mode["array"] = bool(array)
    _np_mode["shape"] = bool(shape)


def reset_np():
    set_np(shape=False, array=False)


def is_np_array():
    return _np_mode["array"]


def is_np_shape():
    return _np_mode["shape"]


def softmax(data, axis=-1, length=None, temperature=None):
    kwargs = {"axis": axis}
    if temperature is not None:
        kwargs["temperature"] = temperature
    if length is not None:
        # variable-length masking (reference: softmax use_length=True);
        # lengths are integer metadata, passed raw alongside the op
        kwargs["length"] = getattr(length, "data_jax", length)
        kwargs["use_length"] = True
    return invoke("softmax", data, **kwargs)


def log_softmax(data, axis=-1):
    return invoke("log_softmax", data, axis=axis)


def masked_softmax(data, mask, axis=-1):
    import numpy as _onp
    m = mask.astype(data.dtype)
    # finite dtype-aware floor: -1e18 overflows float16 to -inf, and an
    # all--inf row softmaxes to NaN; half the dtype minimum keeps
    # fully-masked rows at a uniform finite softmax that the final
    # mask-multiply zeroes (reference masked_softmax returns 0 there)
    big = float(_onp.finfo(_onp.dtype(str(data.dtype))).min) / 2
    return invoke("softmax", data * m + (1.0 - m) * big, axis=axis) * m


def relu(data):
    return invoke("relu", data)


def sigmoid(data):
    return invoke("sigmoid", data)


def erf(data):
    return invoke("erf", data)


def erfinv(data):
    return invoke("erfinv", data)


def one_hot(data, depth, on_value=1.0, off_value=0.0):
    return invoke("one_hot", data, depth=depth, on_value=on_value,
                  off_value=off_value)


def pick(data, index, axis=-1, keepdims=False):
    return invoke("pick", data, index, axis=axis, keepdims=keepdims)


def topk(data, k=1, axis=-1, ret_typ="indices"):
    return invoke("topk", data, k=k, axis=axis, ret_typ=ret_typ)


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    return invoke("batch_dot", lhs, rhs, transpose_a=transpose_a,
                  transpose_b=transpose_b)


def embedding(data, weight, input_dim=None, output_dim=None):
    return invoke("Embedding", data, weight, input_dim=input_dim,
                  output_dim=output_dim)


def gamma(data):
    return invoke("gamma", data)


# -- neural-net blocks (reference: npx.* over the FCompute nn ops) ---------
def activation(data, act_type="relu"):
    return invoke("Activation", data, act_type=act_type)


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    if bias is None or no_bias:
        return invoke("FullyConnected", x, weight,
                      num_hidden=num_hidden or weight.shape[0],
                      no_bias=True, flatten=flatten)
    return invoke("FullyConnected", x, weight, bias,
                  num_hidden=num_hidden or weight.shape[0],
                  no_bias=False, flatten=flatten)


def convolution(data, weight, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=None, num_group=1,
                no_bias=False, layout="NCHW"):
    args = [data, weight] + ([] if (bias is None or no_bias) else [bias])
    return invoke("Convolution", *args, kernel=kernel, stride=stride,
                  dilate=dilate, pad=pad,
                  num_filter=num_filter or weight.shape[0],
                  num_group=num_group,
                  no_bias=bias is None or no_bias, layout=layout)


def deconvolution(data, weight, bias=None, **kwargs):
    args = [data, weight] + ([] if bias is None else [bias])
    kwargs.setdefault("no_bias", bias is None)
    return invoke("Deconvolution", *args, **kwargs)


def pooling(data, kernel=None, pool_type="max", stride=None, pad=None,
            global_pool=False, layout="NCHW"):
    return invoke("Pooling", data, kernel=kernel, pool_type=pool_type,
                  stride=stride, pad=pad, global_pool=global_pool,
                  layout=layout)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               axis=1):
    return invoke("BatchNorm", x, gamma, beta, running_mean, running_var,
                  eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                  use_global_stats=use_global_stats, axis=axis)


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return invoke("LayerNorm", data, gamma, beta, axis=axis, eps=eps)


def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    return invoke("GroupNorm", data, gamma, beta, num_groups=num_groups,
                  eps=eps)


def dropout(data, p=0.5, mode="training", axes=None):
    return invoke("Dropout", data, p=p, mode=mode, axes=axes)


def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, **kwargs):
    args = [data] if gamma is None else [data, gamma]
    return invoke("LeakyReLU", *args, act_type=act_type, slope=slope,
                  **kwargs)


def rnn(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0, **kwargs):
    args = [data, parameters, state]
    if state_cell is not None:
        args.append(state_cell)
    return invoke("RNN", *args, state_size=state_size,
                  num_layers=num_layers, mode=mode,
                  bidirectional=bidirectional, p=p, **kwargs)


def reshape_like(lhs, rhs):
    return invoke("reshape_like", lhs, rhs)


def arange_like(data, start=0.0, step=1.0, axis=None):
    return invoke("_contrib_arange_like", data, start=start, step=step,
                  axis=axis)


def broadcast_like(lhs, rhs):
    return invoke("broadcast_like", lhs, rhs)


def gather_nd(data, indices):
    return invoke("gather_nd", data, indices)


def scatter_nd(data, indices, shape):
    return invoke("scatter_nd", data, indices, shape=shape)


def smooth_l1(data, scalar=1.0):
    return invoke("smooth_l1", data, scalar=scalar)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if sequence_length is not None:
        return invoke("SequenceMask", data, sequence_length,
                      use_sequence_length=True, value=value, axis=axis)
    return invoke("SequenceMask", data, use_sequence_length=False,
                  value=value, axis=axis)


def cast(data, dtype):
    return invoke("cast", data, dtype=dtype)


def seed(s):
    from . import random as _random
    _random.seed(s)


def waitall():
    from .ndarray import ndarray as _nd
    _nd.waitall()


def save(file, arrays):
    """npx.save — dict-or-list NDArray serialization (reference:
    numpy_extension/utils.py save/load over the .params container)."""
    from .ndarray.ndarray import save as _nd_save
    _nd_save(file, arrays)


def load(file):
    from .ndarray.ndarray import load as _nd_load
    out = _nd_load(file)
    if isinstance(out, dict):
        return {k: _as_np(v) for k, v in out.items()}
    return _as_np(out)


def interleaved_matmul_selfatt_qk(queries_keys_values, heads):
    """reference: _contrib_interleaved_matmul_selfatt_qk (transformer.cc),
    the npx spelling GluonNLP's attention cells call."""
    return invoke("_contrib_interleaved_matmul_selfatt_qk",
                  queries_keys_values, heads=heads)


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads):
    return invoke("_contrib_interleaved_matmul_selfatt_valatt",
                  queries_keys_values, attention, heads=heads)


__all__ += ["interleaved_matmul_selfatt_qk",
            "interleaved_matmul_selfatt_valatt"]
