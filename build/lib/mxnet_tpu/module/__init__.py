"""Module API. reference: python/mxnet/module/__init__.py."""
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup
from .module import Module
from .bucketing_module import BucketingModule

__all__ = ["BaseModule", "Module", "BucketingModule",
           "DataParallelExecutorGroup"]
