"""BucketingModule: variable-length sequence training via per-bucket
executors sharing parameters.

TPU-native analog of reference python/mxnet/module/bucketing_module.py. Each
bucket key gets its own Module bound on that bucket's shapes; parameters are
shared through the default bucket. On TPU each bucket is its own XLA
compilation (shape-specialized executable) — the exact analog of the
reference's per-bucket GraphExecutors sharing memory.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


def _inherit_optimizer(module, source):
    """Share one optimizer/kvstore/updater across bucket modules (one
    parameter set, many executors)."""
    module.optimizer_initialized = True
    module._optimizer = source._optimizer
    module._kvstore = source._kvstore
    module._update_on_kvstore = source._update_on_kvstore
    module._updater = source._updater


class BucketingModule(BaseModule):
    """reference: module/bucketing_module.py (BucketingModule)."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        datas, labels, _ = sym_gen(default_bucket_key)
        self._default_names = (datas, labels)
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._context = context
        self._work_load_list = work_load_list
        self._compression_params = compression_params
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self._monitor = None
        self._grad_req = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    def _gen_symbol(self, key):
        symbol, data_names, label_names = self._call_sym_gen(key)
        return symbol, data_names, label_names

    def get_params(self):
        """reference: BucketingModule.get_params."""
        assert self.binded and self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Binds the default bucket. reference: BucketingModule.bind."""
        assert shared_module is None, \
            "shared_module for BucketingModule is not supported"
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._grad_req = grad_req

        symbol, data_names, label_names = self._gen_symbol(
            self._default_bucket_key)
        module = Module(symbol, data_names, label_names, logger=self.logger,
                        context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names,
                        compression_params=self._compression_params)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=self._grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """reference: BucketingModule.switch_bucket."""
        assert self.binded, "call bind before switching bucket"
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._gen_symbol(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names,
                            compression_params=self._compression_params)
            module.bind(data_shapes, label_shapes, self._curr_module.
                        for_training, self._curr_module.inputs_need_grad,
                        force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key],
                        grad_req=self._grad_req)
            # a bucket created AFTER init_optimizer must inherit the shared
            # optimizer/updater, or its update() would assert (reference:
            # switch_bucket borrows the default bucket's optimizer state)
            if self.optimizer_initialized:
                _inherit_optimizer(module,
                                   self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                _inherit_optimizer(mod, self._curr_module)
        self.optimizer_initialized = True

    def prepare(self, data_batch, sparse_row_id_fn=None):
        assert self.binded
        bucket_key = data_batch.bucket_key
        original_bucket_key = self._curr_bucket_key
        data_shapes = data_batch.provide_data
        label_shapes = data_batch.provide_label
        self.switch_bucket(bucket_key, data_shapes, label_shapes)
        self.switch_bucket(original_bucket_key, None, None)

    def forward(self, data_batch, is_train=None):
        """reference: BucketingModule.forward — switches bucket first."""
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        # share current params into the new bucket's executors
        if self._curr_module is not self._buckets[self._default_bucket_key]:
            arg_p, aux_p = self._buckets[self._default_bucket_key].get_params()
            self._curr_module.set_params(arg_p, aux_p)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        self._curr_module.update()
        # propagate updated params back to the default bucket
        if self._curr_module is not self._buckets[self._default_bucket_key]:
            arg_p, aux_p = self._curr_module.get_params()
            self._buckets[self._default_bucket_key].set_params(arg_p, aux_p)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._curr_module.get_input_grads(
            merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)
