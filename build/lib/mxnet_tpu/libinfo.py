"""`mx.libinfo` — build/version info.

reference: python/mxnet/libinfo.py (__version__, find_lib_path,
find_include_path). There is no libmxnet.so here — the "library" is the
native host-kernel .so plus the JAX/XLA runtime; find_lib_path points at
the former when built.
"""
from __future__ import annotations

import os

from .base import __version__  # noqa: F401  (re-export, reference parity)

__all__ = ["__version__", "find_lib_path", "find_include_path",
           "features"]


def find_lib_path():
    """Path(s) to the native host-kernel library, if built."""
    from .native import lib, _OUT
    return [_OUT] if lib() is not None and os.path.exists(_OUT) else []


def find_include_path():
    """Native sources directory (the ctypes ABI has no headers)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(os.path.dirname(here), "native")


def features():
    from .runtime import Features
    return Features()
