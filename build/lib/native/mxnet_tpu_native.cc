// Native host-side kernels for the data pipeline.
//
// TPU-native analog of the reference's C++ IO stack: dmlc RecordIO framing
// (reference: 3rdparty/dmlc-core/include/dmlc/recordio.h,
// src/recordio.cc) and the image pipeline's decode/augment hot loops
// (reference: src/io/image_aug_default.cc, iter_image_recordio_2.cc).
// Device compute belongs to XLA/Pallas; what stays on the host — scanning
// record framing and converting uint8 HWC images to normalized float CHW
// batches — is exactly the part the reference kept in C++, so it is C++
// here too. Built lazily by mxnet_tpu/native/__init__.py with g++ -O3
// -fopenmp; every entry point has a pure-python fallback.
//
// ABI: plain extern "C", ctypes-friendly (no pybind11 in this image).

#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

namespace {
constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLengthMask = (1u << 29) - 1;
inline uint32_t load_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
}  // namespace

extern "C" {

// Scan a whole .rec buffer and emit logical-record (start, payload_size)
// pairs; multi-part records (cflag 1/2/3) collapse into one logical record
// whose size is the sum of part payloads. Returns the record count, or
// -1 on a corrupt magic, -2 when out capacity is exhausted.
int64_t mxtpu_recordio_index(const uint8_t* buf, int64_t n,
                             int64_t* starts, int64_t* sizes,
                             int64_t max_records) {
  int64_t pos = 0, count = 0;
  int64_t cur_start = -1, cur_size = 0;
  while (pos + 8 <= n) {
    if (load_u32(buf + pos) != kMagic) return -1;
    const uint32_t lrec = load_u32(buf + pos + 4);
    const uint32_t cflag = (lrec >> 29) & 7u;
    const uint32_t length = lrec & kLengthMask;
    const int64_t payload = pos + 8;
    if (payload + length > n) break;  // truncated tail: stop cleanly
    const int64_t padded = (length + 3u) & ~3llu;
    if (cflag == 0 || cflag == 1) {   // start of a logical record
      cur_start = pos;
      cur_size = length;
    } else {
      cur_size += length;
    }
    if (cflag == 0 || cflag == 3) {   // end of a logical record
      if (count == max_records) return -2;
      starts[count] = cur_start;
      sizes[count] = cur_size;
      ++count;
    }
    pos = payload + padded;
  }
  return count;
}

// Fused uint8 HWC -> float32 CHW normalize: dst[c][h][w] =
// (src[h][w][c]/255 - mean[c]) / std[c]. One pass, no numpy temporaries
// (reference pipeline: image_aug_default.cc TensorRGB conversion).
void mxtpu_img_to_chw_norm(const uint8_t* src, int64_t h, int64_t w,
                           int64_t c, const float* mean, const float* stdv,
                           float* dst) {
  const int64_t plane = h * w;
  for (int64_t ch = 0; ch < c; ++ch) {
    const float m = mean ? mean[ch] : 0.0f;
    const float inv = 1.0f / (stdv ? stdv[ch] : 1.0f);
    float* out = dst + ch * plane;
    const uint8_t* in = src + ch;
    for (int64_t i = 0; i < plane; ++i) {
      out[i] = ((in[i * c] * (1.0f / 255.0f)) - m) * inv;
    }
  }
}

// Batch variant, OpenMP across images (reference: the decode thread pool of
// ImageRecordIOParser2). src is B contiguous HWC uint8 images.
void mxtpu_batch_to_chw_norm(const uint8_t* src, int64_t b, int64_t h,
                             int64_t w, int64_t c, const float* mean,
                             const float* stdv, float* dst) {
  const int64_t in_stride = h * w * c;
  const int64_t out_stride = c * h * w;
#if defined(_OPENMP)
#pragma omp parallel for schedule(static)
#endif
  for (int64_t i = 0; i < b; ++i) {
    mxtpu_img_to_chw_norm(src + i * in_stride, h, w, c, mean, stdv,
                          dst + i * out_stride);
  }
}

int mxtpu_version() { return 1; }

}  // extern "C"
